"""Quantized grouped (MoE expert) GEMM parity — ops/pallas/grouped_matmul.py
``gmm_quant`` and the identical-math fallbacks in ops/grouped_gemm.py.

The fused dispatch is the default quantized-MoE serving path, so its
numerics are pinned against dequantize-then-``ragged_dot`` for every
scheme, across expert counts and ragged group splits (empty groups
included). The ragged/gathered fallbacks must be BIT-identical to
dequantize-at-entry (same decode, same ops, same order); the Pallas
kernel runs in interpret mode (tier-1 is CPU) against the same
reference. A poison monkeypatch proves serving never falls back to
whole-tree dequantization, and a TP+EP mesh case pins the sharded
carrier plan.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu.ops.grouped_gemm as gg
from deepspeed_tpu.inference.quantization.quantization import (QuantizedWeight,
                                                               _quantize_grouped)
from deepspeed_tpu.ops.grouped_gemm import (dropless_moe_ffn, grouped_gemm,
                                            grouped_gemm_any, moe_grouped_mlp)
from deepspeed_tpu.ops.pallas.grouped_matmul import _fit_tile, gmm_quant_supported

SCHEMES = ("int8", "fp8", "fp6")


def _qstack(rng, e, k, n, scheme, group, scale=0.1):
    w = jnp.asarray(rng.randn(e, k, n).astype(np.float32) * scale)
    qw = _quantize_grouped(w, scheme, group, dequant_dtype=jnp.float32)
    assert isinstance(qw, QuantizedWeight), (scheme, e, k, n, group)
    return qw


def _idx_from_sizes(sizes):
    """Expert index vector realizing exact per-expert group sizes."""
    return jnp.asarray(np.repeat(np.arange(len(sizes)), sizes), jnp.int32)


class TestRaggedParity:
    """grouped_gemm_any over carriers vs dequantize-then-ragged_dot must
    be BIT-identical: the quantized forward is literally the same decode
    feeding the same ragged_dot."""

    @pytest.mark.parametrize("scheme", SCHEMES)
    @pytest.mark.parametrize("sizes", [
        (5, 3, 8),          # plain ragged
        (0, 9, 0, 7),       # empty experts interleaved
        (16,),              # single expert
        (1, 1, 1, 1, 1, 1, 1, 1),  # all-singleton groups
    ])
    def test_bit_identical_to_dequant_then_ragged(self, scheme, sizes):
        rng = np.random.RandomState(hash((scheme, sizes)) % 2**31)
        E, D, F = len(sizes), 48, 64
        qw = _qstack(rng, E, D, F, scheme, 16)
        x = jnp.asarray(rng.randn(int(sum(sizes)), D).astype(np.float32))
        gs = jnp.asarray(sizes, jnp.int32)
        ref = grouped_gemm(x, qw.dequantized(jnp.float32), gs)
        got = grouped_gemm_any(x, qw, gs)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    @pytest.mark.parametrize("scheme", SCHEMES)
    @pytest.mark.parametrize("num_experts,t", [(4, 32), (7, 21), (16, 64)])
    def test_moe_mlp_bit_identical(self, scheme, num_experts, t):
        """Full quantized MoE FFN (ragged path, T >= E) vs the same MLP
        over dequantize-at-entry stacks."""
        rng = np.random.RandomState(hash((scheme, num_experts, t)) % 2**31)
        D, F = 32, 48
        wg = _qstack(rng, num_experts, D, F, scheme, 16)
        wu = _qstack(rng, num_experts, D, F, scheme, 16)
        wd = _qstack(rng, num_experts, F, D, scheme, 16)
        x = jnp.asarray(rng.randn(t, D).astype(np.float32))
        idx = jnp.asarray(rng.randint(0, num_experts, (t,)), jnp.int32)
        ref = moe_grouped_mlp(x, idx, wg.dequantized(jnp.float32),
                              wu.dequantized(jnp.float32),
                              wd.dequantized(jnp.float32), num_experts)
        got = moe_grouped_mlp(x, idx, wg, wu, wd, num_experts)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    @pytest.mark.parametrize("scheme", SCHEMES)
    def test_gathered_decode_path_bit_identical(self, scheme):
        """rows < experts routes to the gathered contraction; gather
        commutes with elementwise dequant, so still bitwise equal."""
        rng = np.random.RandomState(hash(scheme) % 2**31)
        E, D, F, t = 16, 32, 48, 3
        wg = _qstack(rng, E, D, F, scheme, 16)
        wu = _qstack(rng, E, D, F, scheme, 16)
        wd = _qstack(rng, E, F, D, scheme, 16)
        x = jnp.asarray(rng.randn(t, D).astype(np.float32))
        idx = jnp.asarray(rng.randint(0, E, (t,)), jnp.int32)
        gg.GMM_STATS.reset()
        ref = moe_grouped_mlp(x, idx, wg.dequantized(jnp.float32),
                              wu.dequantized(jnp.float32),
                              wd.dequantized(jnp.float32), E)
        got = moe_grouped_mlp(x, idx, wg, wu, wd, E)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        snap = gg.GMM_STATS.snapshot()
        assert snap.get("gathered_quant") and snap.get("gathered")


class TestPallasInterpret:
    """The fused ``gmm_quant`` kernel (interpret mode on CPU) against the
    ragged dequant reference — fp32 dequant target, so the only
    difference is dot accumulation order."""

    @pytest.mark.parametrize("scheme", SCHEMES)
    def test_kernel_matches_ragged(self, scheme):
        rng = np.random.RandomState(hash(("pallas", scheme)) % 2**31)
        E, D, F, t = 4, 128, 128, 64
        wg = _qstack(rng, E, D, F, scheme, 32)
        wu = _qstack(rng, E, D, F, scheme, 32)
        wd = _qstack(rng, E, F, D, scheme, 32)
        assert gmm_quant_supported(wg.values, wg.scales, scheme)
        x = jnp.asarray(rng.randn(t, D).astype(np.float32))
        idx = jnp.asarray(rng.randint(0, E, (t,)), jnp.int32)
        ref = moe_grouped_mlp(x, idx, wg.dequantized(jnp.float32),
                              wu.dequantized(jnp.float32),
                              wd.dequantized(jnp.float32), E)
        gg.GMM_STATS.reset()
        gg.FORCE_INTERPRET = True
        try:
            got = moe_grouped_mlp(x, idx, wg, wu, wd, E)
        finally:
            gg.FORCE_INTERPRET = False
        assert gg.GMM_STATS.snapshot().get("pallas_quant")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_kernel_empty_expert_and_grad(self):
        rng = np.random.RandomState(41)
        E, D, F = 4, 128, 128
        wg = _qstack(rng, E, D, F, "int8", 32)
        wu = _qstack(rng, E, D, F, "int8", 32)
        wd = _qstack(rng, E, F, D, "int8", 32)
        t = 48
        x = jnp.asarray(rng.randn(t, D).astype(np.float32))
        idx = jnp.asarray(rng.randint(0, E - 1, (t,)), jnp.int32)  # expert 3 empty

        def loss(x, w1, w3, w2):
            return (moe_grouped_mlp(x, idx, w1, w3, w2, E) ** 2).sum()

        ref = jax.grad(loss)(x, wg.dequantized(jnp.float32),
                             wu.dequantized(jnp.float32),
                             wd.dequantized(jnp.float32))
        gg.FORCE_INTERPRET = True
        try:
            got = jax.grad(loss)(x, wg, wu, wd)
        finally:
            gg.FORCE_INTERPRET = False
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


class TestFrozenBaseGrad:
    """The quantized base is frozen: dx flows, carriers get float0/zero
    cotangents (the OptimizedLinear training contract)."""

    def test_ragged_dx_matches_dense_reference(self):
        rng = np.random.RandomState(43)
        E, D, F, t = 4, 32, 48, 24
        qw = _qstack(rng, E, D, F, "int8", 16)
        gs = jnp.asarray([8, 0, 10, 6], jnp.int32)
        x = jnp.asarray(rng.randn(t, D).astype(np.float32))

        def loss_q(x):
            return (grouped_gemm_any(x, qw, gs) ** 2).sum()

        def loss_d(x):
            return (grouped_gemm(x, qw.dequantized(jnp.float32), gs) ** 2).sum()

        np.testing.assert_allclose(np.asarray(jax.grad(loss_q)(x)),
                                   np.asarray(jax.grad(loss_d)(x)),
                                   rtol=1e-5, atol=1e-6)

    def test_carriers_receive_no_cotangent(self):
        rng = np.random.RandomState(47)
        qw = _qstack(rng, 2, 16, 32, "fp8", 16)
        gs = jnp.asarray([3, 5], jnp.int32)
        x = jnp.asarray(rng.randn(8, 16).astype(np.float32))

        def loss(values, scales):
            return (gg._ragged_qdot(x, values, scales, gs, "fp8",
                                    jnp.dtype(jnp.float32)) ** 2).sum()

        dv, ds = jax.grad(loss, argnums=(0, 1), allow_int=True)(qw.values,
                                                                qw.scales)
        # fp8 carriers: zeros of the carrier dtype; scales: exact zeros
        assert not np.asarray(ds).any()
        assert not np.asarray(dv, np.float32).any()


class TestFitTileRaises:
    """_fit_tile must fail loudly (naming dim and floor) instead of
    silently degrading to unusable 8-row tiles."""

    def test_undivisible_dim_raises(self):
        with pytest.raises(ValueError, match="1042"):
            _fit_tile(256, 1042)

    def test_aligned_dims_still_resolve(self):
        assert _fit_tile(256, 1024) == 256
        assert _fit_tile(256, 8) == 8
        assert _fit_tile(8, 1048) == 8  # 1048 % 8 == 0: floor tile is legal


class TestKillSwitch:
    """DS_FUSED_GMM=0 restores dequantize-at-entry; outputs stay
    bit-identical either way (the A/B contract the bench lane relies
    on)."""

    @pytest.mark.parametrize("scheme", SCHEMES)
    def test_off_matches_on_bitwise(self, scheme, monkeypatch):
        rng = np.random.RandomState(hash(("ks", scheme)) % 2**31)
        E, D, F, t = 4, 32, 48, 20
        wg = _qstack(rng, E, D, F, scheme, 16)
        wu = _qstack(rng, E, D, F, scheme, 16)
        wd = _qstack(rng, E, F, D, scheme, 16)
        x = jnp.asarray(rng.randn(t, D).astype(np.float32))
        idx = jnp.asarray(rng.randint(0, E, (t, 2)), jnp.int32)
        vals = jnp.full((t, 2), 0.5, jnp.float32)
        on = dropless_moe_ffn(x, idx, vals, wg, wu, wd, E)
        monkeypatch.setenv("DS_FUSED_GMM", "0")
        assert not gg.fused_gmm_enabled()
        off = dropless_moe_ffn(x, idx, vals, wg, wu, wd, E)
        np.testing.assert_array_equal(np.asarray(on), np.asarray(off))


class TestUnboxNeverCalled:
    """Serving must keep the MoE subtree boxed: whole-tree
    dequantization is poisoned and the fused path must not trip it."""

    def _poison(self, monkeypatch):
        def boom(tree, dtype=jnp.bfloat16):
            raise AssertionError("dequantize_tree called on the fused MoE path")
        import deepspeed_tpu.inference.quantization as qpkg
        import deepspeed_tpu.inference.quantization.quantization as qmod
        monkeypatch.setattr(qmod, "dequantize_tree", boom)
        if hasattr(qpkg, "dequantize_tree"):
            monkeypatch.setattr(qpkg, "dequantize_tree", boom)

    def test_v2_moe_mlp_never_unboxes_tree(self, monkeypatch):
        from deepspeed_tpu.inference.v2.model_runner import _moe_mlp
        rng = np.random.RandomState(53)
        E, D, F, t, k = 4, 32, 48, 10, 2
        p = {
            "gate": {"wg": {"kernel": _quantize_grouped(
                jnp.asarray(rng.randn(D, E).astype(np.float32) * 0.1),
                "int8", 16, dequant_dtype=jnp.float32)}},
            "experts_w1": _qstack(rng, E, D, F, "int8", 16),
            "experts_w3": _qstack(rng, E, D, F, "int8", 16),
            "experts_w2": _qstack(rng, E, F, D, "int8", 16),
        }
        x = jnp.asarray(rng.randn(t, D).astype(np.float32))
        from deepspeed_tpu.inference.quantization.quantization import dequantize_tree
        ref = _moe_mlp(x, dequantize_tree(p, jnp.float32), k)
        self._poison(monkeypatch)
        got = _moe_mlp(x, p, k)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_kill_switch_still_unboxes(self, monkeypatch):
        """DS_FUSED_GMM=0 must take the poisoned entry path — proves the
        poison actually guards the branch the fused path skips."""
        from deepspeed_tpu.inference.v2.model_runner import _moe_mlp
        rng = np.random.RandomState(59)
        E, D = 4, 32
        p = {
            "gate": {"wg": {"kernel": jnp.asarray(
                rng.randn(D, E).astype(np.float32))}},
            "experts_w1": _qstack(rng, E, D, 48, "int8", 16),
            "experts_w3": _qstack(rng, E, D, 48, "int8", 16),
            "experts_w2": _qstack(rng, E, 48, D, "int8", 16),
        }
        x = jnp.asarray(rng.randn(6, D).astype(np.float32))
        self._poison(monkeypatch)
        monkeypatch.setenv("DS_FUSED_GMM", "0")
        with pytest.raises(AssertionError, match="dequantize_tree"):
            _moe_mlp(x, p, 2)


class TestShardedCarriers:
    """TP+EP mesh: stacked carriers cross the shard_map boundary
    destructured, E/ep per expert shard, psum combine — against the
    single-shard dense reference."""

    @pytest.mark.parametrize("scheme", SCHEMES)
    def test_tp_ep_matches_single_shard(self, scheme):
        from deepspeed_tpu.parallel import groups
        from deepspeed_tpu.parallel.topology import make_mesh_topology
        rng = np.random.RandomState(hash(("tp_ep", scheme)) % 2**31)
        E, D, F, t, k = 4, 64, 128, 16, 2
        wg = _qstack(rng, E, D, F, scheme, 32)
        wu = _qstack(rng, E, D, F, scheme, 32)
        wd = _qstack(rng, E, F, D, scheme, 32)
        x = jnp.asarray(rng.randn(t, D).astype(np.float32))
        idx = jnp.asarray(rng.randint(0, E, (t, k)), jnp.int32)
        vals = jnp.asarray(rng.rand(t, k).astype(np.float32))
        vals = vals / vals.sum(-1, keepdims=True)
        ref = dropless_moe_ffn(x, idx, vals, wg.dequantized(jnp.float32),
                               wu.dequantized(jnp.float32),
                               wd.dequantized(jnp.float32), E)
        mesh = make_mesh_topology(expert=2, tensor=2, data=1,
                                  devices=jax.devices()[:4])
        groups.set_mesh(mesh)
        try:
            got = dropless_moe_ffn(x, idx, vals, wg, wu, wd, E, mesh=mesh)
            dense = dropless_moe_ffn(x, idx, vals,
                                     wg.dequantized(jnp.float32),
                                     wu.dequantized(jnp.float32),
                                     wd.dequantized(jnp.float32), E,
                                     mesh=mesh)
        finally:
            groups.destroy_mesh()
        # quantized-sharded vs dense-sharded: identical math modulo the
        # fp32 psum; both sit within reduction-order noise of the
        # single-shard reference
        np.testing.assert_allclose(np.asarray(got), np.asarray(dense),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_expert_only_psum_when_tensor_unshardable(self):
        """F not divisible by tp → the plan degrades to expert-only
        sharding (replicated features) and still matches."""
        from deepspeed_tpu.inference.v2.sharding import moe_expert_specs
        from deepspeed_tpu.parallel.topology import make_mesh_topology
        rng = np.random.RandomState(61)
        E, D = 4, 64
        mesh = make_mesh_topology(expert=2, tensor=2, data=1,
                                  devices=jax.devices()[:4])
        # ng = 60/12 = 5 groups: neither divisible by tp=2 nor a single
        # group, so the column scales cannot follow a tensor split
        wg = _qstack(rng, E, D, 60, "int8", 12)
        wu = _qstack(rng, E, D, 60, "int8", 12)
        wd = _qstack(rng, E, 60, D, "int8", 12)
        specs, psum_axes = moe_expert_specs(mesh, wg, wu, wd)
        assert psum_axes == ("expert",)
        for w_specs in specs:
            for sp in w_specs:
                assert "tensor" not in jax.tree.leaves(tuple(sp))
