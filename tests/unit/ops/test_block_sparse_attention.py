"""Pallas block-sparse attention: parity vs masked-dense + work-ratio.

Mirrors the reference's tests/unit/ops/sparse_attention/ (triton SDD/DSD
kernel checks): the block-skipping kernels must match the masked-dense
path exactly (block-granular semantics) and must visit only ~density of
the dense block grid at BigBird sparsity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.block_sparse_attention import (block_sparse_attention,
                                                             grid_fraction,
                                                             layout_to_indices)
from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                FixedSparsityConfig,
                                                SparseSelfAttention)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import layout_to_mask
from deepspeed_tpu.models.llama import einsum_attention


def _qkv(B, S, H, D, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
    return mk(), mk(), mk()


def _dense_ref(q, k, v, layout, block):
    mask = layout_to_mask(layout, block, q.shape[1])[None]
    return einsum_attention(q, k, v, causal=False, mask=mask)


@pytest.mark.parametrize("cfg_cls,kw", [
    (BigBirdSparsityConfig, dict(num_random_blocks=1, num_sliding_window_blocks=3,
                                 num_global_blocks=1)),
    (FixedSparsityConfig, dict(num_local_blocks=4, num_global_blocks=1)),
    (FixedSparsityConfig, dict(num_local_blocks=4, num_global_blocks=1,
                               attention="unidirectional")),
])
def test_forward_matches_masked_dense(cfg_cls, kw):
    B, S, H, D, block = 2, 128, 2, 32, 16
    cfg = cfg_cls(num_heads=H, block=block, **kw)
    layout = cfg.make_layout(S)
    q, k, v = _qkv(B, S, H, D)
    out = block_sparse_attention(q, k, v, layout, block, interpret=True)
    want = _dense_ref(q, k, v, layout, block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_per_head_layouts():
    B, S, H, D, block = 1, 64, 3, 16, 16
    cfg = BigBirdSparsityConfig(num_heads=H, block=block, different_layout_per_head=True,
                                num_random_blocks=1, num_sliding_window_blocks=1,
                                num_global_blocks=1)
    layout = cfg.make_layout(S)
    assert not (layout[0] == layout[1]).all() or not (layout[0] == layout[2]).all()
    q, k, v = _qkv(B, S, H, D, seed=3)
    out = block_sparse_attention(q, k, v, layout, block, interpret=True)
    want = _dense_ref(q, k, v, layout, block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_backward_matches_masked_dense():
    B, S, H, D, block = 1, 64, 2, 16, 16
    cfg = BigBirdSparsityConfig(num_heads=H, block=block, num_random_blocks=1,
                                num_sliding_window_blocks=3, num_global_blocks=1)
    layout = cfg.make_layout(S)
    q, k, v = _qkv(B, S, H, D, seed=1)
    co = jnp.asarray(np.random.RandomState(2).randn(B, S, H, D).astype(np.float32))

    def loss_kernel(q, k, v):
        return jnp.sum(block_sparse_attention(q, k, v, layout, block, interpret=True) * co)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_ref(q, k, v, layout, block) * co)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gk, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5,
                                   err_msg=f"d{name}")


def test_bigbird_4k_work_ratio():
    """At S=4k BigBird sparsity the kernels must do ~density of the dense
    work (the reference's whole point — matmul.py:819 skips blocks; the
    masked-dense path burns 100%). Counted via the grid: one step per
    admitted (head, q-block, k-block) pair, global rows included."""
    S, block = 4096, 64
    cfg = BigBirdSparsityConfig(num_heads=1, block=block, num_random_blocks=1,
                                num_sliding_window_blocks=3, num_global_blocks=1)
    layout = cfg.make_layout(S)
    density = layout.mean()
    assert density < 0.15, f"BigBird@4k should be sparse, got {density:.3f}"
    # the fori_loop bound per row is its admitted count, so total executed
    # block pairs == admitted pairs == density x dense, exactly
    k_idx, k_nnz, q_idx, q_nnz = layout_to_indices(layout)
    H, nq, nk = layout.shape
    assert int(k_nnz.sum()) == int(layout.sum()) == int(q_nnz.sum())
    assert grid_fraction(layout) == pytest.approx(density)


def test_ragged_rows_and_empty_row():
    """Rows with very different admitted counts must each accumulate
    exactly their own pairs; a row with NO admitted blocks outputs zeros
    (and contributes zero dk/dv) instead of garbage."""
    B, S, H, D, block = 1, 64, 1, 16, 16
    layout = np.zeros((1, 4, 4), bool)
    layout[0, 0] = [True, True, True, True]   # row 0: all 4
    layout[0, 1] = [False, True, False, False]  # row 1: only block 1
    layout[0, 2] = [False, False, False, False]  # row 2: EMPTY
    layout[0, 3] = [False, False, False, True]
    q, k, v = _qkv(B, S, H, D, seed=5)
    out = np.asarray(block_sparse_attention(q, k, v, layout, block, interpret=True))
    want = np.asarray(_dense_ref(q, k, v, layout, block))
    np.testing.assert_allclose(out[:, :32], want[:, :32], rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(out[:, 48:], want[:, 48:], rtol=2e-5, atol=2e-5)
    assert np.all(out[:, 32:48] == 0.0)  # empty row → zeros


def test_sparse_self_attention_dispatches_kernel():
    B, S, H, D, block = 1, 64, 2, 16, 16
    cfg = FixedSparsityConfig(num_heads=H, block=block, num_local_blocks=2,
                              num_global_blocks=1)
    q, k, v = _qkv(B, S, H, D, seed=7)
    dense = SparseSelfAttention(cfg, force_kernel=False)(q, k, v)
    kern = SparseSelfAttention(cfg, force_kernel=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(kern), np.asarray(dense), rtol=2e-5, atol=2e-5)


def test_indices_structure():
    layout = np.zeros((2, 3, 3), bool)
    layout[0, 0, [0, 2]] = True
    layout[0, 1, 1] = True
    layout[1, 2, [0, 1, 2]] = True
    k_idx, k_nnz, q_idx, q_nnz = layout_to_indices(layout)
    assert k_nnz[0].tolist() == [2, 1, 0] and k_idx[0, 0, :2].tolist() == [0, 2]
    assert k_nnz[1].tolist() == [0, 0, 3]
    # transpose: head 1's key-block 0 admitted by query-block 2
    assert q_nnz[1].tolist() == [1, 1, 1] and q_idx[1, 0, 0] == 2


class TestSparseAttentionUtils:
    """ds_config parsing + pad/unpad + position extension (reference
    sparse_attention_utils.py + runtime/config.py get_sparse_attention)."""

    def test_config_modes(self):
        from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                        get_sparse_attention_config)
        ds = {"sparse_attention": {"mode": "bigbird", "block": 32,
                                   "num_random_blocks": 2,
                                   "num_sliding_window_blocks": 3,
                                   "num_global_blocks": 1}}
        cfg = get_sparse_attention_config(ds, num_heads=4)
        assert isinstance(cfg, BigBirdSparsityConfig)
        assert cfg.block == 32 and cfg.num_random_blocks == 2 and cfg.num_heads == 4
        assert get_sparse_attention_config({}, num_heads=4) is None
        with pytest.raises(NotImplementedError, match="sparsity mode"):
            get_sparse_attention_config({"sparse_attention": {"mode": "nope"}}, 4)

    def test_build_and_run_from_ds_config(self):
        from deepspeed_tpu.ops.sparse_attention import build_sparse_self_attention
        attn = build_sparse_self_attention(
            {"sparse_attention": {"mode": "fixed", "block": 16,
                                  "num_local_blocks": 2, "num_global_blocks": 1}},
            num_heads=2)
        q, k, v = _qkv(1, 64, 2, 16, seed=11)
        out = attn(q, k, v)
        assert out.shape == (1, 64, 2, 16)

    def test_pad_unpad_roundtrip(self):
        from deepspeed_tpu.ops.sparse_attention import SparseAttentionUtils
        ids = np.arange(2 * 45).reshape(2, 45)
        pad_len, pids, mask, *_ = SparseAttentionUtils.pad_to_block_size(
            16, ids, pad_token_id=9)
        assert pad_len == 3 and pids.shape == (2, 48)
        assert (pids[:, -3:] == 9).all() and (mask[:, -3:] == 0).all()
        seq_out = np.random.RandomState(0).randn(2, 48, 8)
        unp = SparseAttentionUtils.unpad_sequence_output(pad_len, seq_out)
        assert unp.shape == (2, 45, 8)
        assert SparseAttentionUtils.unpad_sequence_output(0, seq_out).shape == (2, 48, 8)

    def test_extend_position_embedding(self):
        from deepspeed_tpu.ops.sparse_attention import SparseAttentionUtils
        params = {"model": {"embed_positions": np.arange(12.0).reshape(6, 2),
                            "layers": {"w": np.ones((2, 2))}}}
        out = SparseAttentionUtils.extend_position_embedding(params, 15)
        table = out["model"]["embed_positions"]
        assert table.shape == (15, 2)
        np.testing.assert_array_equal(table[6:12], table[:6])  # tiled
        np.testing.assert_array_equal(out["model"]["layers"]["w"], np.ones((2, 2)))
