"""Dropless MoE routing (drop_tokens=False) + gate jitter.

Reference match: ``deepspeed/moe/sharded_moe.py:186,212`` (no-drop
gather path — Mixtral-style training routes every token to its full
top-k) and ``:55`` (``multiplicative_jitter`` under
``noisy_gate_policy='Jitter'``). TPU mechanism under test: the serving
grouped GEMM (``lax.ragged_dot`` over expert-sorted rows) as the
training dispatch, differentiated end-to-end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.moe.sharded_moe import MOELayer, multiplicative_jitter


def _x(B=2, S=8, D=16, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(B, S, D).astype(np.float32))


class TestDroplessRouting:

    def test_dropless_matches_manual_topk(self):
        """Every token reaches its full top-k: the layer output equals the
        hand-computed dense mixture (no capacity truncation anywhere)."""
        x = _x()
        layer = MOELayer(num_experts=4, hidden_size=16, intermediate_size=32,
                         k=2, drop_tokens=False)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        out, aux = layer.apply({"params": params}, x)

        wg = params["gate"]["wg"]["kernel"]
        w1, w3, w2 = (params["experts_w1"], params["experts_w3"], params["experts_w2"])
        flat = x.reshape(-1, 16)
        gates = jax.nn.softmax(flat @ wg, axis=-1)
        tv, ti = jax.lax.top_k(gates, 2)
        tv = tv / tv.sum(-1, keepdims=True)
        h = jax.nn.silu(jnp.einsum("td,edi->tei", flat, w1)) * jnp.einsum(
            "td,edi->tei", flat, w3)
        per_e = jnp.einsum("tei,eid->ted", h, w2)
        want = jnp.einsum("tk,tkd->td", tv,
                          jnp.take_along_axis(per_e, ti[:, :, None], axis=1))
        np.testing.assert_allclose(np.asarray(out).reshape(-1, 16), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
        assert np.isfinite(float(aux))

    def test_dropless_gradients_flow_to_all_experts_and_gate(self):
        x = _x(seed=1)
        layer = MOELayer(num_experts=4, hidden_size=16, intermediate_size=32,
                         k=2, drop_tokens=False)
        params = layer.init(jax.random.PRNGKey(1), x)["params"]

        def loss_fn(p):
            out, aux = layer.apply({"params": p}, x)
            return jnp.sum(out ** 2) + 0.01 * aux

        grads = jax.grad(loss_fn)(params)
        for leaf in jax.tree.leaves(grads):
            assert np.all(np.isfinite(np.asarray(leaf)))
        # the gate gets signal through the combine weights
        assert float(jnp.abs(grads["gate"]["wg"]["kernel"]).max()) > 0

    def test_dropless_beats_capacity_dropped_at_equal_steps(self):
        """With a starving capacity factor the dropped run loses tokens;
        dropless reaches an equal-or-better loss in the same steps."""
        import optax
        x = _x(B=4, S=16, D=16, seed=2)
        target = jnp.asarray(np.random.RandomState(3).randn(4, 16, 16).astype(np.float32))

        def train(drop_tokens, capacity_factor):
            layer = MOELayer(num_experts=4, hidden_size=16, intermediate_size=32,
                             k=2, drop_tokens=drop_tokens,
                             capacity_factor=capacity_factor)
            params = layer.init(jax.random.PRNGKey(0), x)["params"]
            opt = optax.adam(1e-2)
            st = opt.init(params)

            @jax.jit
            def step(p, s):
                def loss_fn(p):
                    out, aux = layer.apply({"params": p}, x)
                    return jnp.mean((out - target) ** 2) + 0.01 * aux
                l, g = jax.value_and_grad(loss_fn)(p)
                u, s = opt.update(g, s)
                return optax.apply_updates(p, u), s, l

            for _ in range(60):
                params, st, loss = step(params, st)
            return float(loss)

        dropped = train(True, capacity_factor=0.25)
        dropless = train(False, capacity_factor=0.25)
        assert dropless <= dropped * 1.02, (dropless, dropped)

    def test_expert_parallel_dropless_matches_single_shard(self):
        """Dropless training under an expert-parallel axis: the manual
        shard_map dispatch (experts stay on their shard, masked local
        routing, psum combine — the serving mechanism) reproduces the
        unsharded dropless layer, forward AND gradients."""
        from deepspeed_tpu.parallel import groups
        from deepspeed_tpu.parallel.topology import make_mesh_topology
        x = _x()
        layer = MOELayer(num_experts=4, hidden_size=16, intermediate_size=32,
                         k=2, drop_tokens=False)
        groups.destroy_mesh()
        params = layer.init(jax.random.PRNGKey(0), x)["params"]

        def loss_fn(p):
            out, aux = layer.apply({"params": p}, x)
            return jnp.sum(out ** 2) + 0.01 * aux

        want_loss, want_grads = jax.value_and_grad(loss_fn)(params)
        groups.destroy_mesh()
        groups.set_mesh(make_mesh_topology(expert=2, data=-1))
        try:
            got_loss, got_grads = jax.jit(jax.value_and_grad(loss_fn))(params)
            np.testing.assert_allclose(float(got_loss), float(want_loss),
                                       rtol=1e-5, atol=1e-5)
            for (ka, a), (kb, b) in zip(
                    jax.tree_util.tree_leaves_with_path(want_grads),
                    jax.tree_util.tree_leaves_with_path(got_grads)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-4, atol=2e-4, err_msg=str(ka))
        finally:
            groups.destroy_mesh()

    def test_moe_layer_passthrough_and_param_tree_stable(self):
        """MoE(drop_tokens=False) produces the same param structure as the
        capacity mode (checkpoints swap between routing modes)."""
        x = _x()
        a = MoE(hidden_size=16, intermediate_size=32, num_experts=4, k=2)
        b = MoE(hidden_size=16, intermediate_size=32, num_experts=4, k=2,
                drop_tokens=False)
        pa = a.init(jax.random.PRNGKey(0), x)["params"]
        pb = b.init(jax.random.PRNGKey(0), x)["params"]
        assert jax.tree.structure(pa) == jax.tree.structure(pb)
        out, _ = b.apply({"params": pa}, x)  # cross-load
        assert np.all(np.isfinite(np.asarray(out)))


class TestGateJitter:

    def test_multiplicative_jitter_bounds(self):
        x = jnp.ones((64, 8))
        y = multiplicative_jitter(x, jax.random.PRNGKey(0), epsilon=1e-2)
        assert float(y.min()) >= 0.99 and float(y.max()) <= 1.01
        assert not np.allclose(np.asarray(y), 1.0)

    @pytest.mark.parametrize("drop_tokens", [True, False])
    def test_jitter_only_in_training(self, drop_tokens):
        x = _x()
        layer = MOELayer(num_experts=4, hidden_size=16, intermediate_size=32, k=2,
                         noisy_gate_policy="Jitter", drop_tokens=drop_tokens)
        params = layer.init(jax.random.PRNGKey(0), x)["params"]
        e1, _ = layer.apply({"params": params}, x, train=False)
        e2, _ = layer.apply({"params": params}, x, train=False)
        np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
        t1, _ = layer.apply({"params": params}, x, train=True,
                            rngs={"dropout": jax.random.PRNGKey(1)})
        t2, _ = layer.apply({"params": params}, x, train=True,
                            rngs={"dropout": jax.random.PRNGKey(2)})
        assert not np.allclose(np.asarray(t1), np.asarray(t2))


def test_engine_dropless_ep2_matches_ep1_losses():
    """Dropless MoE TRAINING under an expert-parallel mesh axis — the
    reference's flagship Mixtral-at-scale configuration
    (``deepspeed/moe/sharded_moe.py:186,212`` no-drop gather with expert
    groups from ``utils/groups.py:114-254``). The ep=2 engine run must
    reproduce the ep=1 loss curve: expert parallelism changes the
    dispatch layout, not the math. (This composition used to
    CHECK-crash XLA — the shard_map boundary's transposed psum of the
    token cotangent ran in bf16; ``ops/grouped_gemm.py`` now widens the
    region boundary to fp32.)"""
    import deepspeed_tpu
    from deepspeed_tpu.models import build_llama
    from deepspeed_tpu.parallel import groups
    ids = np.random.RandomState(0).randint(0, 256, size=(16, 16)).astype(np.int32)

    def run(ep):
        groups.destroy_mesh()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_llama("mixtral-debug", moe_drop_tokens=False),
            config={"train_batch_size": 16, "train_micro_batch_size_per_gpu": 16,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 2}, "bf16": {"enabled": True},
                    "mesh": {"expert_parallel_size": ep, "data_parallel_size": 8 // ep}})
        losses = []
        try:
            for _ in range(4):
                losses.append(float(engine.train_batch(
                    batch=(jnp.asarray(ids)[None], jnp.asarray(ids)[None]))))
        finally:
            groups.destroy_mesh()
        return losses

    l1, l2 = run(1), run(2)
    assert all(b < a for a, b in zip(l1, l1[1:])), f"ep1 not learning: {l1}"
    np.testing.assert_allclose(l1, l2, rtol=2e-2, atol=2e-2)
