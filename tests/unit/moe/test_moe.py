"""MoE gating/dispatch tests (analogue of reference tests/unit/moe)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.moe.sharded_moe import MOELayer, _capacity, top1gating, top2gating, topkgating
from deepspeed_tpu.parallel import groups


class TestGating:

    def test_capacity(self):
        assert _capacity(64, 8, 1, 1.0) == 8
        assert _capacity(64, 8, 2, 1.25) == 20
        assert _capacity(4, 8, 1, 1.0) == 4  # min capacity

    def test_top1_every_token_dispatched_once(self):
        T, E = 32, 4
        logits = jax.random.normal(jax.random.PRNGKey(0), (T, E))
        aux, combine, dispatch = top1gating(logits, capacity_factor=4.0)
        # ample capacity: every token lands in exactly one slot
        assert int(dispatch.sum()) == T
        # combine weights of a dispatched token equal its softmax gate prob
        gates = jax.nn.softmax(logits, axis=-1)
        picked = combine.sum(axis=(1, 2))
        top = gates.max(axis=-1)
        np.testing.assert_allclose(np.asarray(picked), np.asarray(top), rtol=1e-5)

    def test_top2_weights_normalized(self):
        T, E = 32, 4
        logits = jax.random.normal(jax.random.PRNGKey(1), (T, E))
        aux, combine, dispatch = top2gating(logits, capacity_factor=4.0)
        assert int(dispatch.sum()) == 2 * T
        totals = combine.sum(axis=(1, 2))
        np.testing.assert_allclose(np.asarray(totals), np.ones(T), rtol=1e-5)

    def test_capacity_drops_tokens(self):
        T, E = 32, 4
        # all tokens prefer expert 0
        logits = jnp.concatenate([jnp.full((T, 1), 5.0), jnp.zeros((T, E - 1))], axis=-1)
        aux, combine, dispatch = top1gating(logits, capacity_factor=0.5)
        cap = _capacity(T, E, 1, 0.5)
        assert int(dispatch[:, 0].sum()) == cap  # expert 0 full, rest dropped

    def test_aux_loss_uniform_is_one(self):
        # perfectly uniform routing -> aux loss == 1 (E * E * (1/E) * (1/E))
        T, E = 64, 4
        idx = jnp.arange(T) % E
        logits = jax.nn.one_hot(idx, E) * 10.0
        aux, _, _ = top1gating(logits, capacity_factor=2.0)
        np.testing.assert_allclose(float(aux), 1.0, rtol=1e-3)

    def test_top2_renormalized_after_capacity_drop(self):
        T, E = 32, 4
        # expert 0 is everyone's first choice (fills fast); second choices
        # alternate between experts 1 and 2
        rows = [[3.0, 2.0, -5.0, -5.0], [3.0, -5.0, 2.0, -5.0]]
        logits = jnp.array([rows[t % 2] for t in range(T)])
        aux, combine, dispatch = top2gating(logits, capacity_factor=0.5)
        cap = _capacity(T, E, 2, 0.5)
        # tokens that lost expert 0 (over capacity) but kept expert 1 must
        # carry full weight 1.0 on the surviving expert
        kept_only_second = (dispatch[:, 0].sum(-1) == 0) & (dispatch[:, 1].sum(-1) == 1)
        assert bool(kept_only_second.any())
        totals = combine.sum(axis=(1, 2))
        np.testing.assert_allclose(np.asarray(totals[kept_only_second]),
                                   1.0, rtol=1e-5)

    def test_no_capacity_slot_collision(self):
        T, E = 64, 4
        logits = jax.random.normal(jax.random.PRNGKey(2), (T, E))
        _, _, dispatch = topkgating(logits, k=2, capacity_factor=2.0)
        # each (expert, slot) holds at most one token
        per_slot = dispatch.sum(axis=0)
        assert int(per_slot.max()) <= 1


class TestMOELayer:

    def test_forward_shape_and_grad(self):
        groups.initialize_mesh({"expert_parallel_size": 4})
        layer = MOELayer(num_experts=4, hidden_size=16, intermediate_size=32, k=2,
                         capacity_factor=2.0)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
        variables = layer.init(jax.random.PRNGKey(1), x)

        def loss_fn(params):
            out, aux = layer.apply({"params": params}, x)
            return out.sum() + aux

        out, aux = layer.apply(variables, x)
        assert out.shape == x.shape
        assert np.isfinite(float(aux))
        grads = jax.grad(loss_fn)(variables["params"])
        gnorms = [float(jnp.abs(g).sum()) for g in jax.tree.leaves(grads)]
        assert all(np.isfinite(g) for g in gnorms)
        assert any(g > 0 for g in gnorms)
