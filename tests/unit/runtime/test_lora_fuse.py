"""Hybrid-engine LoRA fuse/unfuse (reference hybrid_engine.py:138-146).

The DeepSpeed-Chat LoRA RLHF stage generates through FUSED weights:
``base += a@b*(alpha/r)`` before the rollout, restored afterwards. The
TPU form is a pure params-tree transform; the unchanged module forward
computes the same function because ``lora_b`` is zeroed while fused."""

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.linear.config import LoRAConfig
from deepspeed_tpu.linear.optimized_linear import (OptimizedLinear, fuse_lora_tree,
                                                   has_lora_sites, unfuse_lora_tree)

LORA = LoRAConfig(lora_r=4, lora_alpha=8.0)
ALPHA = LORA.lora_alpha  # rank is derived per site from lora_a's shape


class LoraNet(nn.Module):
    """Two LoRA linears + plain head — a miniature RLHF actor."""

    @nn.compact
    def __call__(self, x, y=None):
        h = nn.gelu(OptimizedLinear(32, lora_config=LORA, dtype=jnp.float32,
                                    name="up")(x))
        h = OptimizedLinear(16, lora_config=LORA, dtype=jnp.float32, name="mid")(h)
        logits = nn.Dense(8, name="head")(h)
        if y is None:
            return logits
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.take_along_axis(logp, y.astype(jnp.int32)[..., None], -1).mean()


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(16, 24).astype(np.float32), rng.randint(0, 8, 16))


class TestLoraFuseTree:

    def test_fuse_preserves_function_and_unfuse_restores(self):
        x, y = _data()
        model = LoraNet()
        params = model.init(jax.random.PRNGKey(0), jnp.asarray(x))["params"]
        # make the adapters nonzero so fusion actually changes the base
        params = jax.tree_util.tree_map_with_path(
            lambda kp, v: v + 0.01 if "lora_b" in str(kp) else v, params)
        assert has_lora_sites(params)
        want = model.apply({"params": params}, jnp.asarray(x))

        fused, stash = fuse_lora_tree(params, ALPHA)
        assert len(stash) == 2
        # lora_b zeroed, base changed
        assert float(jnp.abs(fused["up"]["lora_b"]).max()) == 0.0
        assert not np.allclose(np.asarray(fused["up"]["base_kernel"]),
                               np.asarray(params["up"]["base_kernel"]))
        got = model.apply({"params": fused}, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

        restored = unfuse_lora_tree(fused, stash, ALPHA)
        for (ka, va), (kb, vb) in zip(
                jax.tree_util.tree_leaves_with_path(params),
                jax.tree_util.tree_leaves_with_path(restored)):
            np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                       rtol=1e-6, atol=1e-6, err_msg=str(ka))

    def test_heterogeneous_ranks_fuse_per_site(self):
        """Sites may disagree on rank: scaling must come from each
        site's own ``lora_a`` shape, and a config-global ``lora_r`` hint
        must never override it — otherwise one site's delta is fused at
        the wrong scale and fuse→unfuse stops round-tripping."""
        x, _ = _data()

        class MixedRankNet(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = OptimizedLinear(32, lora_config=LoRAConfig(
                    lora_r=4, lora_alpha=8.0), dtype=jnp.float32,
                    name="wide")(x)
                return OptimizedLinear(16, lora_config=LoRAConfig(
                    lora_r=2, lora_alpha=8.0), dtype=jnp.float32,
                    name="narrow")(nn.gelu(h))

        model = MixedRankNet()
        params = model.init(jax.random.PRNGKey(1), jnp.asarray(x))["params"]
        params = jax.tree_util.tree_map_with_path(
            lambda kp, v: v + 0.02 if "lora_b" in str(kp) else v, params)
        assert params["wide"]["lora_a"].shape[-1] == 4
        assert params["narrow"]["lora_a"].shape[-1] == 2
        want = model.apply({"params": params}, jnp.asarray(x))

        # lora_r=4 is the (wrong-for-one-site) global hint; the per-site
        # rank must win for BOTH the fuse and the unfuse
        fused, stash = fuse_lora_tree(params, 8.0, lora_r=4)
        got = model.apply({"params": fused}, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
        restored = unfuse_lora_tree(fused, stash, 8.0, lora_r=4)
        for (ka, va), (kb, vb) in zip(
                jax.tree_util.tree_leaves_with_path(params),
                jax.tree_util.tree_leaves_with_path(restored)):
            np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                       rtol=1e-6, atol=1e-6, err_msg=str(ka))

    def test_quantized_base_fuses_and_unfuses_bit_exact(self):
        """LoRA fuse over an int8 quantized base (reference
        hybrid_engine.py:138-146 with linear/quantization.py):
        dequantize → fuse → requantize; the stash carries the ORIGINAL
        carrier so unfuse restores it bit-exactly."""
        from deepspeed_tpu.inference.quantization.quantization import _quantize_grouped
        from deepspeed_tpu.linear.config import QuantizationConfig
        model = nn.Sequential([OptimizedLinear(8, lora_config=LORA,
                                               quantization_config=QuantizationConfig(),
                                               dtype=jnp.float32)])
        params = model.init(jax.random.PRNGKey(0), jnp.ones((2, 8)))["params"]
        # give the quantized base real content + nonzero adapters
        # (grouped layout: [in, out] carriers, group width from shapes)
        site = params["layers_0"]
        rng = np.random.RandomState(5)
        w = jnp.asarray(rng.randn(8, 8).astype(np.float32) * 0.1)
        g = site["base_kernel_q"].shape[-1] // site["base_kernel_scales"].shape[-1]
        qw = _quantize_grouped(w, "int8", g)
        vq, sq = qw.values, qw.scales
        site = dict(site, base_kernel_q=vq, base_kernel_scales=sq,
                    lora_b=site["lora_b"] + 0.05)
        params = dict(params, layers_0=site)

        x = jnp.asarray(rng.randn(4, 8).astype(np.float32))
        want = model.apply({"params": params}, x)
        fused, stash = fuse_lora_tree(params, ALPHA)
        assert float(jnp.abs(fused["layers_0"]["lora_b"]).max()) == 0.0
        got = model.apply({"params": fused}, x)
        # requantization error on the fused weight only (int8 group quant)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=0.08, atol=0.02)

        restored = unfuse_lora_tree(fused, stash, ALPHA)
        np.testing.assert_array_equal(np.asarray(restored["layers_0"]["base_kernel_q"]),
                                      np.asarray(vq))
        np.testing.assert_array_equal(np.asarray(restored["layers_0"]["base_kernel_scales"]),
                                      np.asarray(sq))
        np.testing.assert_array_equal(np.asarray(restored["layers_0"]["lora_b"]),
                                      np.asarray(site["lora_b"]))


class TestHybridEngineLoraFuse:

    def _engine(self):
        cfg = {
            "train_batch_size": 16,
            "train_micro_batch_size_per_gpu": 16,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "hybrid_engine": {"enabled": True, "lora_r": LORA.lora_r,
                              "lora_alpha": LORA.lora_alpha},
            "frozen_parameters": ["base_kernel"],
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=LoraNet(), config=cfg)
        return engine

    def test_eval_fuses_train_unfuses_and_logits_match(self):
        from deepspeed_tpu.parallel import groups
        groups.destroy_mesh()
        engine = self._engine()
        x, y = _data()
        # a couple of RLHF "train" steps so the adapters are nonzero-grad
        for _ in range(2):
            loss = engine(jnp.asarray(x), jnp.asarray(y))
            engine.backward(loss)
            engine.step()
        before = jax.tree.map(np.asarray, engine.params)
        want = engine.module.apply({"params": engine.params}, jnp.asarray(x))

        engine.eval()  # reference: eval phase generates through fused weights
        assert engine._lora_stash is not None
        assert float(jnp.abs(engine.params["up"]["lora_b"]).max()) == 0.0
        got = engine.module.apply({"params": engine.params}, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

        engine.train()
        assert engine._lora_stash is None
        for (ka, va), (kb, vb) in zip(
                jax.tree_util.tree_leaves_with_path(before),
                jax.tree_util.tree_leaves_with_path(engine.params)):
            np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                       rtol=1e-5, atol=1e-6, err_msg=str(ka))

    def test_explicit_fuse_is_idempotent(self):
        from deepspeed_tpu.parallel import groups
        groups.destroy_mesh()
        engine = self._engine()
        x, y = _data()
        loss = engine(jnp.asarray(x), jnp.asarray(y))
        engine.backward(loss)
        engine.step()
        engine.fuse_lora_weight(lora_r=LORA.lora_r, lora_alpha=LORA.lora_alpha)
        base1 = np.asarray(engine.params["up"]["base_kernel"])
        engine.fuse_lora_weight(lora_r=LORA.lora_r, lora_alpha=LORA.lora_alpha)
        np.testing.assert_array_equal(base1, np.asarray(engine.params["up"]["base_kernel"]))
        engine.unfuse_lora_weight()
        engine.unfuse_lora_weight()  # second call is a no-op

    def test_save_checkpoint_while_fused_persists_unfused(self, tmp_path):
        """eval() fuses; a checkpoint taken then must still hold the
        UNFUSED view (nonzero lora_b) or resume silently loses adapters."""
        from deepspeed_tpu.parallel import groups
        groups.destroy_mesh()
        engine = self._engine()
        x, y = _data()
        loss = engine(jnp.asarray(x), jnp.asarray(y))
        engine.backward(loss)
        engine.step()
        unfused_b = np.asarray(engine.params["up"]["lora_b"])
        engine.eval()  # fused now
        assert float(jnp.abs(engine.params["up"]["lora_b"]).max()) == 0.0
        engine.save_checkpoint(str(tmp_path), tag="f")
        # still fused after the save (eval mode preserved)
        assert engine._lora_stash is not None
        groups.destroy_mesh()
        e2 = self._engine()
        l2 = e2(jnp.asarray(x), jnp.asarray(y))
        e2.backward(l2)
        e2.step()
        e2.load_checkpoint(str(tmp_path), tag="f")
        np.testing.assert_allclose(np.asarray(e2.params["up"]["lora_b"]), unfused_b,
                                   rtol=1e-6, atol=1e-7)
