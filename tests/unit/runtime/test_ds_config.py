"""Config tests (analogue of reference tests/unit/runtime/test_ds_config_dict.py)."""

import json

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig


class TestBatchConfig:

    def test_all_given(self):
        cfg = DeepSpeedConfig({
            "train_batch_size": 32,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 8,
        })
        assert cfg.train_batch_size == 32
        assert cfg.train_micro_batch_size_per_gpu == 4
        assert cfg.gradient_accumulation_steps == 8

    def test_infer_gas(self):
        cfg = DeepSpeedConfig({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 8})
        assert cfg.gradient_accumulation_steps == 4

    def test_infer_micro(self):
        cfg = DeepSpeedConfig({"train_batch_size": 32, "gradient_accumulation_steps": 4})
        assert cfg.train_micro_batch_size_per_gpu == 8

    def test_infer_train(self):
        cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 8, "gradient_accumulation_steps": 4})
        assert cfg.train_batch_size == 32

    def test_only_train(self):
        cfg = DeepSpeedConfig({"train_batch_size": 32})
        assert cfg.train_micro_batch_size_per_gpu == 32
        assert cfg.gradient_accumulation_steps == 1

    def test_mismatch_raises(self):
        with pytest.raises(AssertionError):
            DeepSpeedConfig({
                "train_batch_size": 33,
                "train_micro_batch_size_per_gpu": 4,
                "gradient_accumulation_steps": 8,
            })

    def test_none_raises(self):
        with pytest.raises(AssertionError):
            DeepSpeedConfig({"gradient_accumulation_steps": 4})

    def test_world_size_triangulation(self):
        class FakeMpu:
            def get_data_parallel_world_size(self):
                return 4

        cfg = DeepSpeedConfig({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4}, mpu=FakeMpu())
        assert cfg.gradient_accumulation_steps == 2


class TestPrecisionConfig:

    def test_bf16(self):
        cfg = DeepSpeedConfig({"train_batch_size": 1, "bf16": {"enabled": True}})
        assert cfg.bfloat16_enabled and not cfg.fp16_enabled

    def test_fp16(self):
        cfg = DeepSpeedConfig({
            "train_batch_size": 1,
            "fp16": {"enabled": True, "initial_scale_power": 8, "loss_scale_window": 500},
        })
        assert cfg.fp16_enabled
        assert cfg.initial_dynamic_scale == 2**8
        assert cfg.dynamic_loss_scale_args["scale_window"] == 500

    def test_both_raises(self):
        with pytest.raises(AssertionError):
            DeepSpeedConfig({
                "train_batch_size": 1,
                "fp16": {"enabled": True},
                "bf16": {"enabled": True},
            })


class TestZeroConfig:

    def test_stage(self):
        cfg = DeepSpeedConfig({"train_batch_size": 1, "zero_optimization": {"stage": 3}})
        assert cfg.zero_enabled
        assert cfg.zero_optimization_stage == 3

    def test_offload(self):
        cfg = DeepSpeedConfig({
            "train_batch_size": 1,
            "zero_optimization": {
                "stage": 3,
                "offload_optimizer": {"device": "cpu", "pin_memory": True},
                "offload_param": {"device": "nvme", "nvme_path": "/tmp/nvme"},
            },
        })
        assert cfg.zero_config.offload_optimizer_device().value == "cpu"
        assert cfg.zero_config.offload_param_device().value == "nvme"

    def test_deprecated_cpu_offload(self):
        cfg = DeepSpeedConfig({
            "train_batch_size": 1,
            "zero_optimization": {"stage": 2, "cpu_offload": True},
        })
        assert cfg.zero_config.offload_optimizer_device().value == "cpu"

    def test_aliases(self):
        cfg = DeepSpeedConfig({
            "train_batch_size": 1,
            "zero_optimization": {"stage": 3, "stage3_max_live_parameters": 12345},
        })
        assert cfg.zero_config.max_live_parameters == 12345


class TestConfigFromFile:

    def test_json_file(self, tmp_path):
        path = tmp_path / "ds_config.json"
        path.write_text(json.dumps({"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 0.1}}}))
        cfg = DeepSpeedConfig(str(path))
        assert cfg.train_batch_size == 8
        assert cfg.optimizer_name == "adam"
        assert cfg.optimizer_params["lr"] == 0.1

    def test_dup_keys_raise(self, tmp_path):
        path = tmp_path / "dup.json"
        path.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
        with pytest.raises(ValueError):
            DeepSpeedConfig(str(path))
