"""MiCS, hybrid engine, PLD, eigenvalue, sparse tensor tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 32


class TestMiCS:

    def test_mics_restricts_param_shards_keeps_global_opt(self):
        """mics_shard_size=4 on data=2 x sequence=4: params partition
        within the sequence sub-group (4-way) and replicate across data;
        optimizer state still shards over all zero axes (8-way)."""
        groups.destroy_mesh()
        cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0,
                                     "mics_shard_size": 4},
               "mesh": {"data_parallel_size": 2, "sequence_parallel_size": 4}}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=HIDDEN, nlayers=2), config=cfg)
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        k = engine.params["linear_0"]["kernel"]
        uniq_param = len({tuple((sl.start, sl.stop) for sl in s.index)
                          for s in k.addressable_shards})
        m = engine.opt_state["exp_avg"]["linear_0"]["kernel"]
        uniq_opt = len({tuple((sl.start, sl.stop) for sl in s.index)
                        for s in m.addressable_shards})
        assert uniq_param == 4, f"params should shard 4-way (MiCS), got {uniq_param}"
        assert uniq_opt == 8, f"opt state should shard over the full zero world, got {uniq_opt}"

    def test_mics_parity_with_full_zero3(self):
        def run(extra):
            groups.destroy_mesh()
            cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                   "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0,
                                         **extra},
                   "mesh": {"data_parallel_size": 2, "sequence_parallel_size": 4}}
            e, _, _, _ = deepspeed_tpu.initialize(
                model=SimpleModel(hidden_dim=HIDDEN, nlayers=2), config=cfg)
            x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
            out = []
            for _ in range(3):
                l = e(x, y); e.backward(l); e.step(); out.append(float(l))
            return out

        base = run({})
        mics = run({"mics_shard_size": 4})
        assert np.allclose(base, mics, rtol=1e-5, atol=1e-6), f"{base} vs {mics}"

    def test_mics_bad_shard_size_raises(self):
        groups.destroy_mesh()
        cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 3, "mics_shard_size": 3},
               "mesh": {"data_parallel_size": 8}}
        with pytest.raises(ValueError, match="mics_shard_size"):
            deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=HIDDEN), config=cfg)


class TestHybridEngine:

    def test_rlhf_train_generate_interleave(self):
        """The RLHF loop: rollout with generate(), then a train step on
        the SAME weights — no copies, fresh rollouts see the update."""
        from deepspeed_tpu.models import build_llama
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine
        groups.destroy_mesh()
        cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "bf16": {"enabled": True}, "zero_optimization": {"stage": 2},
               "hybrid_engine": {"enabled": True},
               "mesh": {"data_parallel_size": 8}}
        engine, _, _, _ = deepspeed_tpu.initialize(model=build_llama("debug"), config=cfg)
        assert isinstance(engine, DeepSpeedHybridEngine)
        ids = (np.arange(8 * 16, dtype=np.int32).reshape(8, 16) % 250)
        loss = engine(ids, ids)
        engine.backward(loss)
        engine.step()

        out1 = engine.generate(ids[:, :8], max_new_tokens=4)
        assert out1.shape == (8, 12)
        # a few strong updates shift the greedy rollout
        for _ in range(5):
            l = engine(ids, ids); engine.backward(l); engine.step()
        out2 = engine.generate(ids[:, :8], max_new_tokens=4)
        assert out2.shape == (8, 12)
        assert not np.array_equal(np.asarray(out1), np.asarray(out2)), \
            "generate() not reading live training weights"
        # greedy decode is causal-consistent: full forward argmax at the
        # prompt boundary equals the first generated token
        logits = engine.module.apply(
            {"params": engine.params}, jnp.asarray(ids[:, :8]))
        first = np.argmax(np.asarray(logits[:, -1, :]), axis=-1)
        assert np.array_equal(first, np.asarray(out2[:, 8]))

    def test_generate_ragged_no_shape_churn(self, monkeypatch):
        """Mixed-length rollouts through the v2 ragged path: ONE compiled
        step serves every prompt-length mix / batch size, and its greedy
        tokens match the per-shape generate() (VERDICT weak: generate
        recompiles per shape)."""
        from deepspeed_tpu.models import build_llama
        groups.destroy_mesh()
        cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 0},
               "hybrid_engine": {"enabled": True},
               "mesh": {"data_parallel_size": 8}}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_llama("debug", remat=False), config=cfg)
        ids = (np.arange(8 * 16, dtype=np.int32).reshape(8, 16) % 250)
        loss = engine(ids, ids); engine.backward(loss); engine.step()

        rng = np.random.RandomState(0)
        mixed = [rng.randint(0, 250, size=n).astype(np.int32) for n in (5, 9, 13)]
        out = engine.generate_ragged(mixed, max_new_tokens=4)
        assert [len(o) for o in out] == [4, 4, 4]
        # parity vs the per-shape dense generate, prompt by prompt
        for prompt, got in zip(mixed, out):
            dense = engine.generate(prompt[None, :], max_new_tokens=4)
            assert got == list(np.asarray(dense[0, len(prompt):])), (got, dense)
        # different shapes reuse the SAME compiled ragged step: count
        # TRACES (jit re-enters ragged_forward only when retracing)
        import deepspeed_tpu.inference.v2.engine_v2 as ev2
        traces = []
        orig = ev2.ragged_forward
        monkeypatch.setattr(ev2, "ragged_forward",
                            lambda *a, **k: (traces.append(1), orig(*a, **k))[1])
        out2 = engine.generate_ragged([mixed[0][:3], mixed[1]], max_new_tokens=6)
        assert [len(o) for o in out2] == [6, 6]
        assert traces == [], f"ragged path retraced {len(traces)}x for new shapes"


class TestPLD:

    def test_theta_anneals(self):
        from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop
        pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
        assert pld.update_state(0) == 1.0
        mid = pld.update_state(100)
        assert 0.5 < mid < 1.0
        assert abs(pld.update_state(10**6) - 0.5) < 1e-6
        assert pld.get_state()["progressive_layer_drop"] is True

    def test_apply_pld_skip_and_keep(self):
        from deepspeed_tpu.runtime.progressive_layer_drop import apply_pld, layer_keep_prob
        h = jnp.ones((2, 4, 8))
        layer = lambda x: x * 3.0
        kept = apply_pld(layer, h, jax.random.PRNGKey(0), keep_prob=1.0)
        np.testing.assert_allclose(np.asarray(kept), 3.0)
        # keep_prob ~ 0: identity
        skipped = apply_pld(layer, h, jax.random.PRNGKey(0), keep_prob=1e-7)
        np.testing.assert_allclose(np.asarray(skipped), 1.0)
        assert layer_keep_prob(0.5, 0, 12) == 1.0
        assert layer_keep_prob(0.5, 12, 12) == 0.5


class TestEigenvalue:

    def test_quadratic_eigenvalue_exact(self):
        """loss = 0.5 x^T A x has Hessian A: power iteration finds its
        max eigenvalue."""
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
        rng = np.random.RandomState(0)
        q, _ = np.linalg.qr(rng.randn(8, 8))
        eigs = np.array([5.0, 3.0, 1.0, 0.5, 0.2, 0.1, 0.05, 0.01])
        A = (q * eigs) @ q.T
        A = jnp.asarray((A + A.T) / 2, jnp.float32)

        loss = lambda p: 0.5 * p["x"] @ A @ p["x"]
        est = Eigenvalue(max_iter=200, tol=1e-4).compute_eigenvalue(
            loss, {"x": jnp.zeros(8, jnp.float32)})
        assert abs(est - 5.0) < 0.05, est

    def test_model_loss_eigenvalue_positive(self):
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
        import flax.linen as nn
        m = SimpleModel(hidden_dim=8, nlayers=1)
        x = np.random.RandomState(0).randn(4, 8).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 8, 4)
        p = m.init(jax.random.PRNGKey(0), x, y)["params"]
        loss = lambda p: m.apply({"params": p}, jnp.asarray(x), jnp.asarray(y))
        est = Eigenvalue(max_iter=50).compute_eigenvalue(loss, p)
        assert np.isfinite(est) and est > 0


class TestSparseTensor:

    def test_coo_roundtrip(self):
        from deepspeed_tpu.runtime.sparse_tensor import SparseTensor
        dense = jnp.zeros((6, 4)).at[2].set(1.5).at[4].set(-2.0)
        st = SparseTensor(dense_tensor=dense)
        assert st.indices.tolist() == [2, 4]
        np.testing.assert_allclose(np.asarray(st.to_dense()), np.asarray(dense))
        sparse, total = st.sparse_size()
        assert sparse < total
