"""0/1 Adam and 1-bit LAMB (analogue of reference
tests/unit/runtime/half_precision/onebit/ TestZeroOneAdam /
TestOneBitLamb)."""

import numpy as np

import jax

import deepspeed_tpu
from deepspeed_tpu.ops.adam.zoadam import ZeroOneAdam
from deepspeed_tpu.parallel import groups
from unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 32


def make_engine(opt_type, opt_params, lr=1e-2):
    groups.destroy_mesh()
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": opt_type, "params": {"lr": lr, **opt_params}},
        "zero_optimization": {"stage": 1},
        "mesh": {"data_parallel_size": 8},
    }
    model = SimpleModel(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine


class TestZeroOneAdam:

    def test_var_schedule_state_machine(self):
        """Variance-refresh intervals double every var_update_scaler
        refreshes (reference zoadam.py:270)."""
        opt = ZeroOneAdam(var_freeze_step=100, var_update_scaler=2)
        # interval 1 for 2 refreshes (steps 1, 2) -> interval 2 for
        # refreshes at steps 4, 6 -> interval 4 at steps 8, 12 ...
        refresh = [s for s in range(1, 16) if opt.is_var_update_step(s)]
        assert refresh == [1, 2, 4, 6, 8, 12], refresh
        # frozen after var_freeze_step
        assert not opt.is_var_update_step(101)
        # engine protocol: exact exchange exactly on refresh steps
        assert not opt.wants_compressed(0)   # next step = 1, refresh
        assert opt.wants_compressed(2)       # next step = 3, no refresh
        # replay after resume-from-earlier works
        opt.is_var_update_step(50)
        assert opt.is_var_update_step(1)

    def test_trains_and_uses_compressed_steps(self):
        engine = make_engine("ZeroOneAdam",
                             {"var_freeze_step": 4, "var_update_scaler": 2})
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        losses = []
        for _ in range(10):
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
        # compressed steps really ran (error feedback materialized)
        assert engine._onebit_efb is not None
        # in-state schedule advanced in lockstep with the host mirror
        st = engine.opt_state
        assert int(st["step"]) == 10
        assert int(st["var_interval"]) >= 2

    def test_variance_frozen_after_freeze_step(self):
        engine = make_engine("ZeroOneAdam",
                             {"var_freeze_step": 2, "var_update_scaler": 16})
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        for _ in range(3):
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
        v_after_freeze = np.concatenate(
            [np.asarray(l).ravel() for l in jax.tree.leaves(engine.opt_state["exp_avg_sq"])])
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        v_next = np.concatenate(
            [np.asarray(l).ravel() for l in jax.tree.leaves(engine.opt_state["exp_avg_sq"])])
        assert np.array_equal(v_after_freeze, v_next)  # frozen exactly


class TestOneBitLamb:

    def test_warmup_matches_trust_ratio_lamb(self):
        """During warmup the loss curve is LAMB-like and finite; the
        frozen-coefficient EMA accumulates."""
        engine = make_engine("OneBitLamb", {"freeze_step": 100}, lr=1e-2)
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        losses = []
        for _ in range(5):
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        coeffs = [float(c) for c in
                  jax.tree.leaves(engine.opt_state["lamb_coeff_freeze"])]
        assert any(c > 0 for c in coeffs)  # EMA moved off its 0 init

    def test_compressed_stage_trains(self):
        engine = make_engine("OneBitLamb", {"freeze_step": 3}, lr=1e-2)
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        losses = []
        for _ in range(12):
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[2], losses
        assert engine._onebit_efb is not None  # 1-bit exchange ran
        # frozen variance: exp_avg_sq stops moving, fresh one keeps moving
        v = np.concatenate([np.asarray(l).ravel()
                            for l in jax.tree.leaves(engine.opt_state["exp_avg_sq"])])
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        v2 = np.concatenate([np.asarray(l).ravel()
                             for l in jax.tree.leaves(engine.opt_state["exp_avg_sq"])])
        assert np.array_equal(v, v2)
        factors = [float(f) for f in jax.tree.leaves(engine.opt_state["last_factor"])]
        assert all(0.5 <= f <= 4.0 for f in factors)

    def test_convergence_vs_uncompressed_lamb(self):
        """Compressed 1-bit LAMB reaches a loss in the same ballpark as
        uncompressed FusedLamb on the same stream (reference
        TestOneBitLambExpAvgMask-style closeness, relaxed)."""
        data = random_dataloader(None, 32, HIDDEN, batch_size=8)

        def run(opt_type, params):
            engine = make_engine(opt_type, params, lr=1e-2)
            losses = []
            for i in range(20):
                x, y = data[i % len(data)]
                loss = engine(x, y)
                engine.backward(loss)
                engine.step()
                losses.append(float(loss))
            return losses

        base = run("Lamb", {})
        onebit = run("OneBitLamb", {"freeze_step": 4})
        assert onebit[-1] < base[0] * 0.9  # it genuinely optimizes
        # same ballpark as the exact optimizer at the end of the run
        assert onebit[-1] < base[-1] * 3 + 1e-3, (onebit[-1], base[-1])
