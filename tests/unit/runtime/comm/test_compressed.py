"""ZeRO++ compressed-collective tests.

Mirrors the reference's qgZ/qwZ coverage
(tests/unit/runtime/zero/test_zeropp.py + coalesced_collectives tests):
numerics of the int8 collectives against their exact counterparts, loss
parity of the quantized engine paths, and — the contract VERDICT asked
for — that the flags visibly change the lowered collective dtypes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import make_mesh_topology
from deepspeed_tpu.runtime.comm.compressed import (quant_all_gather, quant_all_reduce,
                                                   quant_reduce_scatter)
from unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 32


def _mesh():
    groups.destroy_mesh()
    mesh = make_mesh_topology(data=8)
    groups.set_mesh(mesh)
    return mesh


class TestCollectives:

    def test_quant_reduce_scatter_matches_exact(self):
        mesh = _mesh()
        rng = np.random.RandomState(0)
        x = rng.uniform(-1, 1, size=(8, 256)).astype(np.float32)

        f = jax.jit(jax.shard_map(
            lambda c: quant_reduce_scatter(c[0], "data", 0, stochastic=False),
            mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_vma=False))
        got = np.asarray(f(x))
        want = x.sum(axis=0)
        assert got.shape == want.shape
        assert np.abs(got - want).max() < 0.05, np.abs(got - want).max()

    def test_quant_all_gather_roundtrip(self):
        mesh = _mesh()
        rng = np.random.RandomState(1)
        x = rng.uniform(-2, 2, size=(8, 64)).astype(np.float32)
        f = jax.jit(jax.shard_map(
            lambda c: quant_all_gather(c[0], "data", 0, dtype=jnp.float32),
            mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False))
        got = np.asarray(f(x))
        want = x.reshape(-1)
        assert got.shape == want.shape
        assert np.abs(got - want).max() < 2 * (2.0 / 127), np.abs(got - want).max()

    def test_quant_all_gather_hpz_two_hop(self):
        mesh = _mesh()
        rng = np.random.RandomState(2)
        x = rng.uniform(-1, 1, size=(8, 48)).astype(np.float32)
        f = jax.jit(jax.shard_map(
            lambda c: quant_all_gather(c[0], "data", 0, hpz_size=4, dtype=jnp.float32),
            mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False))
        got = np.asarray(f(x))
        want = x.reshape(-1)
        assert np.abs(got - want).max() < 2 * (1.0 / 127), np.abs(got - want).max()

    def test_quant_all_reduce_matches_psum(self):
        mesh = _mesh()
        rng = np.random.RandomState(3)
        x = rng.uniform(-1, 1, size=(8, 33)).astype(np.float32)  # odd size: pad path
        f = jax.jit(jax.shard_map(
            lambda c: quant_all_reduce(c[0], "data", stochastic=False),
            mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False))
        got = np.asarray(f(x))
        want = x.sum(axis=0)
        assert got.shape == want.shape
        assert np.abs(got - want).max() < 0.15, np.abs(got - want).max()


def make_engine(stage=2, extra_zero=None):
    groups.destroy_mesh()
    config = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage, **(extra_zero or {})},
        "mesh": {"data_parallel_size": 8},
    }
    model = SimpleModel(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine


def train(engine, n):
    # one fixed batch, repeated: loss must fall as the model memorizes it
    x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
    losses = []
    for _ in range(n):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def _vag_hlo(engine):
    """Compiled HLO of the gradient program on a representative batch."""
    x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
    args = engine._shard_batch((x, y))
    scale = engine.scaler_state["cur_scale"]
    rng = jax.random.PRNGKey(0)
    fn = engine._value_and_grad_fn()
    return fn.lower(engine.params, scale, rng, args, {}).compile().as_text()


class TestEngineZeroPP:

    def test_qgz_loss_parity_and_int8_wire(self):
        base = make_engine(2)
        base_losses = train(base, 5)
        base_hlo = _vag_hlo(base)

        qg = make_engine(2, {"zero_quantized_gradients": True})
        qg_losses = train(qg, 5)
        qg_hlo = _vag_hlo(qg)

        assert np.isfinite(qg_losses).all()
        assert np.allclose(base_losses, qg_losses, rtol=0.05, atol=0.05), \
            f"{base_losses} vs {qg_losses}"
        assert qg_losses[-1] < qg_losses[0], "no learning under qgZ"
        # the contract: flags change the wire format of the reduction
        assert "s8" in qg_hlo and "all-to-all" in qg_hlo, "no int8 all-to-all lowered"
        assert "s8" not in base_hlo

    def test_qwz_stage3_int8_weight_gather(self):
        base = make_engine(3, {"stage3_param_persistence_threshold": 0})
        base_losses = train(base, 4)

        qw = make_engine(3, {"zero_quantized_weights": True,
                             "stage3_param_persistence_threshold": 0})
        qw_losses = train(qw, 4)
        qw_hlo = _vag_hlo(qw)

        assert np.isfinite(qw_losses).all()
        assert np.allclose(base_losses, qw_losses, rtol=0.1, atol=0.1), \
            f"{base_losses} vs {qw_losses}"
        assert "s8" in qw_hlo and "all-gather" in qw_hlo, "no int8 all-gather lowered"

    def test_qwz_hpz_compiles_and_learns(self):
        e = make_engine(3, {"zero_quantized_weights": True,
                            "zero_hpz_partition_size": 4,
                            "stage3_param_persistence_threshold": 0})
        losses = train(e, 4)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses

    def test_qgz_qwz_llama_with_tp(self):
        """The full composition: ZeRO-3 + qgZ + qwZ with TP constraints
        inside the manual-'data' region (live_spec drops manual axes)."""
        from deepspeed_tpu.models import build_llama
        groups.destroy_mesh()
        mesh = make_mesh_topology(data=4, tensor=2)
        groups.set_mesh(mesh)
        model = build_llama("debug")
        config = {
            "train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2, "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "zero_quantized_gradients": True,
                                  "zero_quantized_weights": True,
                                  "stage3_param_persistence_threshold": 0},
        }
        e, _, _, _ = deepspeed_tpu.initialize(model=model, config=config, mesh=mesh)
        ids = (np.arange(8 * 32, dtype=np.int32).reshape(8, 32) % 256)
        losses = [float(e.train_batch(batch=(ids, ids))) for _ in range(3)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses

    def test_qwz_bf16_without_qgz(self):
        """Regression: qwZ alone under bf16 aborted XLA's CPU backend —
        bf16 psum/psum_scatter of grad cotangents inside the manual
        region ('Invalid binary instruction opcode copy'); the exact
        collectives now run their wire in fp32."""
        from deepspeed_tpu.models import build_llama
        groups.destroy_mesh()
        config = {
            "train_batch_size": 8, "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "zero_quantized_weights": True,
                                  "stage3_param_persistence_threshold": 0},
            "mesh": {"data_parallel_size": 8},
        }
        e, _, _, _ = deepspeed_tpu.initialize(model=build_llama("debug"), config=config)
        ids = (np.arange(8 * 32, dtype=np.int32).reshape(8, 32) % 256)
        losses = [float(e.train_batch(batch=(ids, ids))) for _ in range(4)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses

    def test_qgz_fused_train_batch(self):
        qg = make_engine(2, {"zero_quantized_gradients": True})
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        losses = [float(qg.train_batch(batch=(x, y))) for _ in range(4)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], losses
