"""1-bit compressed communication tests (analogue of reference
tests/unit/runtime/half_precision/onebit/test_onebit.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import make_mesh_topology
from deepspeed_tpu.runtime.comm.onebit import _pack_signs, _unpack_signs, onebit_allreduce
from unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 32


def test_sign_pack_roundtrip():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(256).astype(np.float32))
    packed = _pack_signs(x)
    assert packed.dtype == jnp.uint8 and packed.shape == (32,)  # 8 values/byte
    signs = _unpack_signs(packed, 256)
    assert np.array_equal(np.asarray(signs), np.where(np.asarray(x) >= 0, 1.0, -1.0))


def test_onebit_allreduce_error_feedback_converges():
    """Compression error with feedback is bounded; the mean estimate
    tracks the true mean direction."""
    groups.destroy_mesh()
    mesh = make_mesh_topology(data=8)
    groups.set_mesh(mesh)
    rng = np.random.RandomState(1)
    x = rng.randn(8, 64).astype(np.float32)

    def step(c, e):
        out, e_new = jax.shard_map(
            lambda cc, ee: onebit_allreduce(cc[0], "data", ee[0]),
            mesh=mesh, in_specs=(P("data"), P("data")),
            out_specs=(P(), P("data")), check_vma=False)(c, e)
        return out, e_new

    e = np.zeros_like(x)
    out, e = jax.jit(step)(jnp.asarray(x), jnp.asarray(e))
    true_mean = x.mean(axis=0)
    got = np.asarray(out)
    # sign-compressed estimate correlates strongly with the true mean
    corr = np.corrcoef(got, true_mean)[0, 1]
    assert corr > 0.5, corr
    # error feedback holds the residual (input - decompressed own chunk)
    assert np.isfinite(np.asarray(e)).all()
    assert np.abs(np.asarray(e)).max() > 0


def make_engine(freeze_step, lr=1e-2):
    groups.destroy_mesh()
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "OneBitAdam",
                      "params": {"lr": lr, "freeze_step": freeze_step}},
        "zero_optimization": {"stage": 1},
        "mesh": {"data_parallel_size": 8},
    }
    model = SimpleModel(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine


def test_onebit_adam_warmup_matches_adam():
    """Before freeze_step the trajectory equals plain Adam's."""
    groups.destroy_mesh()
    cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 1}, "mesh": {"data_parallel_size": 8}}
    adam, _, _, _ = deepspeed_tpu.initialize(
        model=SimpleModel(hidden_dim=HIDDEN, nlayers=2), config=cfg)
    ob = make_engine(freeze_step=100)
    x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
    la, lb = [], []
    for _ in range(4):
        l1 = adam(x, y); adam.backward(l1); adam.step(); la.append(float(l1))
        l2 = ob(x, y); ob.backward(l2); ob.step(); lb.append(float(l2))
    assert np.allclose(la, lb, rtol=1e-5, atol=1e-6), f"{la} vs {lb}"


def test_onebit_adam_compressed_stage_trains():
    """Past freeze_step: variance frozen, grads 1-bit — still learns."""
    engine = make_engine(freeze_step=2)
    x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
    losses = []
    for _ in range(10):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[1], losses
    # error feedback materialized once compression kicked in
    assert engine._onebit_efb is not None
    leaf = jax.tree.leaves(engine._onebit_efb)[0]
    assert leaf.shape[0] == 8  # one residual per data rank


def test_onebit_train_batch_path():
    # freeze_step must leave the variance warm (the reference warns a
    # too-early freeze leaves near-zero v and explodes the step size)
    engine = make_engine(freeze_step=3)
    x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
    losses = [float(engine.train_batch(batch=(x, y))) for _ in range(8)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    assert engine._onebit_efb is not None  # compressed path really ran
