"""LR schedule tests (analogue of reference tests/unit/runtime/test_lr_schedulers.py)."""

import math

import pytest

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.runtime.lr_schedules import (LRRangeTest, OneCycle, WarmupCosineLR, WarmupDecayLR, WarmupLR)


def opt(lr=0.01):
    return FusedAdam(lr=lr)


class TestWarmupLR:

    def test_reaches_max(self):
        o = opt()
        s = WarmupLR(o, warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10)
        for _ in range(15):
            s.step()
        assert o.param_groups[0]["lr"] == pytest.approx(0.1)

    def test_linear_midpoint(self):
        o = opt()
        s = WarmupLR(o, warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10, warmup_type="linear")
        # after construction, step() was called once (iteration 0)
        for _ in range(5):
            s.step()
        assert o.param_groups[0]["lr"] == pytest.approx(0.05)

    def test_log_shape(self):
        o = opt()
        s = WarmupLR(o, warmup_min_lr=0.0, warmup_max_lr=1.0, warmup_num_steps=100, warmup_type="log")
        s.step(50)
        expected = math.log(51) / math.log(100)
        assert o.param_groups[0]["lr"] == pytest.approx(expected)


class TestWarmupDecayLR:

    def test_decays_to_zero(self):
        o = opt()
        s = WarmupDecayLR(o, total_num_steps=20, warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10)
        for _ in range(25):
            s.step()
        assert o.param_groups[0]["lr"] == pytest.approx(0.0)

    def test_peak_at_warmup_end(self):
        o = opt()
        s = WarmupDecayLR(o, total_num_steps=20, warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10,
                          warmup_type="linear")
        s.step(10)
        assert o.param_groups[0]["lr"] == pytest.approx(0.1)


class TestWarmupCosineLR:

    def test_cosine_tail(self):
        o = opt(lr=0.1)
        s = WarmupCosineLR(o, total_num_steps=100, warmup_num_steps=10, cos_min_ratio=0.1)
        s.step(100)
        assert o.param_groups[0]["lr"] == pytest.approx(0.1 * 0.1, rel=1e-2)


class TestLRRangeTest:

    def test_continuous_growth(self):
        o = opt()
        s = LRRangeTest(o, lr_range_test_min_lr=0.01, lr_range_test_step_size=10, lr_range_test_step_rate=1.0)
        lrs = []
        for _ in range(30):
            s.step()
            lrs.append(o.param_groups[0]["lr"])
        assert all(b >= a for a, b in zip(lrs, lrs[1:]))
        assert lrs[-1] == pytest.approx(0.01 * (1 + 3.0))

    def test_staircase(self):
        o = opt()
        s = LRRangeTest(o, lr_range_test_min_lr=0.01, lr_range_test_step_size=10, lr_range_test_step_rate=1.0,
                        lr_range_test_staircase=True)
        seen = set()
        for _ in range(30):
            s.step()
            seen.add(round(o.param_groups[0]["lr"], 8))
        assert len(seen) <= 4  # discrete stairs


class TestOneCycle:

    def test_cycle_peak_and_return(self):
        o = opt()
        s = OneCycle(o, cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=10, cycle_momentum=False)
        lrs = []
        for _ in range(20):
            s.step()
            lrs.append(o.param_groups[0]["lr"])
        assert max(lrs) == pytest.approx(0.1, rel=1e-6)
        assert lrs[-1] == pytest.approx(0.01, rel=1e-2)

    def test_state_dict_roundtrip(self):
        o = opt()
        s = OneCycle(o, cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=10, cycle_momentum=False)
        for _ in range(7):
            s.step()
        sd = s.state_dict()
        o2 = opt()
        s2 = OneCycle(o2, cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=10, cycle_momentum=False)
        s2.load_state_dict(sd)
        s.step()
        s2.step()
        assert o.param_groups[0]["lr"] == o2.param_groups[0]["lr"]


def test_onecycle_cycle_momentum():
    """Regression: (mom, 0.99) beta tuples must broadcast per group, not be
    misread as a per-group list."""
    from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
    from deepspeed_tpu.runtime.lr_schedules import OneCycle
    opt = FusedAdam(lr=1e-3)
    sched = OneCycle(opt, cycle_min_lr=1e-4, cycle_max_lr=1e-3, cycle_momentum=True,
                     cycle_min_mom=0.85, cycle_max_mom=0.95)
    assert sched.cycle_momentum
    assert opt.param_groups[0]["betas"] == (0.85, 0.99)
    for _ in range(3):
        sched.step()
    b1, b2 = opt.param_groups[0]["betas"]
    assert 0.84 <= b1 <= 0.96 and b2 == 0.99
