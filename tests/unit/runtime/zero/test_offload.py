"""ZeRO-Offload tests (analogue of reference tests/unit/runtime/zero
offload coverage + tests/perf/adam_test.py numerics).

Properties verified:
- the native SIMD CPU Adam matches the NumPy/XLA Adam math;
- `"offload_optimizer": {"device": "cpu"}` really moves master weights +
  moments to host NumPy buffers (no device arrays for optimizer state);
- loss trajectories match the non-offload engine;
- NVMe offload (device: nvme) swaps moments through the AIO library with
  the same results;
- checkpoint save/load round-trips host state.
"""

import os
import shutil

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 64


def _has_cxx():
    return shutil.which("g++") is not None or shutil.which("c++") is not None


def run_engine(offload=None, steps=6, stage=1, dtype_cfg=None, hidden=HIDDEN, fused=False, opt="Adam"):
    groups.destroy_mesh()
    zero_cfg = {"stage": stage}
    if offload:
        zero_cfg["offload_optimizer"] = offload
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 16,
        "optimizer": {"type": opt, "params": {"lr": 1e-2}},
        "zero_optimization": zero_cfg,
        "mesh": {"data_parallel_size": 8},
    }
    config.update(dtype_cfg or {})
    model = SimpleModel(hidden_dim=hidden, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    batches = random_dataloader(None, 16 * steps, hidden, batch_size=16)
    losses = []
    for x, y in batches:
        if fused:
            losses.append(float(engine.train_batch(batch=(x, y))))
        else:
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
    return losses, engine


@pytest.mark.skipif(not _has_cxx(), reason="no C++ toolchain")
def test_native_cpu_adam_matches_reference():
    from op_builder.tpu import CPUAdamBuilder
    mod = CPUAdamBuilder().load()
    n = 40_001  # odd size exercises the scalar tail
    rng = np.random.default_rng(7)
    p = rng.standard_normal(n).astype(np.float32)
    g = rng.standard_normal(n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    p_ref, m_ref, v_ref = p.copy(), m.copy(), v.copy()
    lr, b1, b2, eps, wd = 1e-3, 0.9, 0.999, 1e-8, 0.01
    for step in (1, 2, 3):
        mod.set_adamw_mode(True)
        mod.adam_update(0, step, lr, b1, b2, eps, wd, True, p, g, m, v)
        # NumPy AdamW reference
        m_ref = b1 * m_ref + (1 - b1) * g
        v_ref = b2 * v_ref + (1 - b2) * g * g
        bc1, bc2 = 1 - b1**step, 1 - b2**step
        p_ref = p_ref - lr * ((m_ref / bc1) / (np.sqrt(v_ref / bc2) + eps) + wd * p_ref)
    assert np.allclose(p, p_ref, rtol=1e-5, atol=1e-6)
    assert np.allclose(m, m_ref, rtol=1e-5, atol=1e-6)
    assert np.allclose(v, v_ref, rtol=1e-5, atol=1e-6)


@pytest.mark.skipif(not _has_cxx(), reason="no C++ toolchain")
def test_native_bf16_roundtrip():
    import ml_dtypes
    from op_builder.tpu import CPUAdamBuilder
    mod = CPUAdamBuilder().load()
    x = np.random.default_rng(0).standard_normal(1001).astype(np.float32)
    u16 = np.empty(1001, np.uint16)
    mod.fp32_to_bf16(x, u16)
    expect = x.astype(ml_dtypes.bfloat16)
    assert np.array_equal(u16.view(ml_dtypes.bfloat16), expect)
    back = np.empty(1001, np.float32)
    mod.bf16_to_fp32(u16, back)
    assert np.array_equal(back, expect.astype(np.float32))


@pytest.mark.parametrize("fused", [False, True])
def test_cpu_offload_matches_device_path(fused):
    """fp32: host SIMD Adam trajectory == device XLA Adam trajectory."""
    base, base_engine = run_engine(offload=None, fused=fused)
    off, off_engine = run_engine(offload={"device": "cpu"}, fused=fused)
    assert np.allclose(base, off, rtol=1e-4, atol=1e-5), f"{base} vs {off}"
    # Optimizer state must actually live on host
    assert off_engine.opt_state is None and off_engine.master_params is None
    ho = off_engine._host_offload
    assert isinstance(ho.master_flat, np.ndarray)
    assert all(isinstance(s, np.ndarray) for s in ho.state_flat.values())
    # The device path keeps jax Arrays
    assert base_engine.opt_state is not None


def test_cpu_offload_bf16():
    """bf16 compute params: the fused fp32->bf16 copy path stays close to
    the device update (small drift from independent bf16 roundings)."""
    base, _ = run_engine(offload=None, dtype_cfg={"bf16": {"enabled": True}})
    off, engine = run_engine(offload={"device": "cpu"}, dtype_cfg={"bf16": {"enabled": True}})
    assert np.allclose(base, off, rtol=5e-2, atol=5e-2), f"{base} vs {off}"
    assert engine.params and jax.tree.leaves(engine.params)[0].dtype == jnp.bfloat16


@pytest.mark.skipif(not _has_cxx(), reason="no C++ toolchain (AIO)")
def test_nvme_offload(tmp_path):
    off, engine = run_engine(offload={"device": "nvme", "nvme_path": str(tmp_path)})
    base, _ = run_engine(offload=None)
    assert np.allclose(base, off, rtol=1e-4, atol=1e-5)
    # moments live in swap files, not RAM
    assert engine._host_offload.state_flat is None
    swapdir = os.path.join(str(tmp_path), "zero_stage_optimizer_swap")
    assert os.path.isfile(os.path.join(swapdir, "exp_avg.swp"))
    assert os.path.isfile(os.path.join(swapdir, "exp_avg_sq.swp"))


def test_offload_checkpoint_roundtrip(tmp_path):
    _, engine = run_engine(offload={"device": "cpu"}, steps=3)
    engine.save_checkpoint(str(tmp_path), tag="t1")
    master_before = engine._host_offload.master_flat.copy()
    m_before = engine._host_offload.state_flat["exp_avg"].copy()

    groups.destroy_mesh()
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1, "offload_optimizer": {"device": "cpu"}},
        "mesh": {"data_parallel_size": 8},
    }
    model = SimpleModel(hidden_dim=HIDDEN, nlayers=2)
    engine2, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    engine2.load_checkpoint(str(tmp_path), tag="t1")
    # state applies at first materialization
    batches = random_dataloader(None, 16, HIDDEN, batch_size=16)
    x, y = batches[0]
    loss = engine2(x, y)
    engine2.backward(loss)
    assert np.allclose(engine2._host_offload.master_flat, master_before)
    assert np.allclose(engine2._host_offload.state_flat["exp_avg"], m_before)


def test_offload_lion_and_adagrad():
    for opt in ("Lion", "Adagrad"):
        base, _ = run_engine(offload=None, steps=3, opt=opt)
        off, _ = run_engine(offload={"device": "cpu"}, steps=3, opt=opt)
        assert np.allclose(base, off, rtol=1e-4, atol=1e-5), f"{opt}: {base} vs {off}"


@pytest.mark.parametrize("opt", ["Lion", "Adagrad", "AdamW"])
def test_offload_bf16_keeps_compute_dtype(opt):
    """Regression: non-native update paths return the fp32 master view —
    the uploaded params must still be cast to the compute dtype, or HBM
    use doubles and every jitted fn retraces."""
    _, engine = run_engine(offload={"device": "cpu"}, steps=2, opt=opt,
                           dtype_cfg={"bf16": {"enabled": True}})
    for leaf in jax.tree.leaves(engine.params):
        assert leaf.dtype == jnp.bfloat16, f"{opt} offload leaked {leaf.dtype} params"
