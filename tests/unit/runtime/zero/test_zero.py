"""ZeRO stage tests (analogue of reference tests/unit/runtime/zero/test_zero.py).

The central correctness property: every ZeRO stage is numerically
equivalent to plain data-parallel training (stage 0), and the optimizer
math matches an unsharded reference implementation.
"""

import os

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 64


def run_engine(stage, dtype_cfg, steps=6, gas=1, hidden=HIDDEN, seed=42, lr=1e-2, extra_zero=None, opt="Adam"):
    groups.destroy_mesh()
    zero_cfg = {"stage": stage}
    zero_cfg.update(extra_zero or {})
    config = {
        "train_batch_size": 16 * gas,
        "train_micro_batch_size_per_gpu": 16,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": opt, "params": {"lr": lr}},
        "zero_optimization": zero_cfg,
        "mesh": {"data_parallel_size": 8},
    }
    config.update(dtype_cfg)
    model = SimpleModel(hidden_dim=hidden, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    batches = random_dataloader(None, 16 * gas * steps, hidden, batch_size=16)
    losses = []
    for x, y in batches:
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses, engine


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_stage_matches_dp_baseline(stage):
    """ZeRO-n loss trajectory == plain DP (stage 0) trajectory."""
    base, _ = run_engine(0, {"bf16": {"enabled": True}})
    test, _ = run_engine(stage, {"bf16": {"enabled": True}})
    assert np.allclose(base, test, rtol=1e-5, atol=1e-5), f"stage {stage}: {base} vs {test}"


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_stage_fp32_matches_reference_adam(stage):
    """fp32 engine result == hand-rolled Adam on the same data."""
    losses, engine = run_engine(stage, {}, steps=4)

    # Hand-rolled reference: same init (same rng), same data, plain Adam.
    model = SimpleModel(hidden_dim=HIDDEN, nlayers=2)
    params = model.init(jax.random.PRNGKey(42), np.zeros((16, HIDDEN), np.float32),
                        np.zeros((16,), np.int64))["params"]
    m = jax.tree.map(jnp.zeros_like, params)
    v = jax.tree.map(jnp.zeros_like, params)
    lr, b1, b2, eps = 1e-2, 0.9, 0.999, 1e-8
    batches = random_dataloader(None, 16 * 4, HIDDEN, batch_size=16)
    ref_losses = []

    @jax.jit
    def step(params, m, v, t, x, y):
        def loss_fn(p):
            return model.apply({"params": p}, x, y)

        loss, g = jax.value_and_grad(loss_fn)(params)
        m = jax.tree.map(lambda mm, gg: b1 * mm + (1 - b1) * gg, m, g)
        v = jax.tree.map(lambda vv, gg: b2 * vv + (1 - b2) * gg**2, v, g)
        bc1 = 1 - b1**t
        bc2 = 1 - b2**t
        params = jax.tree.map(lambda p, mm, vv: p - lr * (mm / bc1) / (jnp.sqrt(vv / bc2) + eps), params, m, v)
        return params, m, v, loss

    for t, (x, y) in enumerate(batches, start=1):
        params, m, v, loss = step(params, m, v, float(t), x, y)
        ref_losses.append(float(loss))

    assert np.allclose(losses, ref_losses, rtol=2e-4, atol=2e-4), f"{losses} vs {ref_losses}"


def test_stage3_params_are_sharded():
    _, engine = run_engine(3, {"bf16": {"enabled": True}}, steps=1, extra_zero={
        "stage3_param_persistence_threshold": 0})
    mesh_size = 8
    sharded = 0
    for leaf in jax.tree.leaves(engine.params):
        n_shards = len({s.index for s in leaf.addressable_shards})
        if leaf.ndim > 0 and leaf.shape[0] * leaf.size >= 0 and n_shards > 1:
            sharded += 1
    assert sharded > 0, "no parameter was actually sharded under stage 3"


def test_stage1_opt_state_sharded_params_replicated():
    _, engine = run_engine(1, {"bf16": {"enabled": True}}, steps=1)
    for leaf in jax.tree.leaves(engine.params):
        assert len({s.index for s in leaf.addressable_shards}) == 1, "stage1 params must be replicated"
    any_sharded = any(
        len({s.index for s in leaf.addressable_shards}) > 1
        for leaf in jax.tree.leaves(engine.opt_state["exp_avg"]))
    assert any_sharded, "stage1 optimizer state must be sharded"


def test_persistence_threshold_keeps_small_replicated():
    _, engine = run_engine(3, {"bf16": {"enabled": True}}, steps=1,
                           extra_zero={"stage3_param_persistence_threshold": 10**9})
    for leaf in jax.tree.leaves(engine.params):
        assert len({s.index for s in leaf.addressable_shards}) == 1


def test_gradient_accumulation_equivalence():
    """gas=2 with half-size micro-batches == gas=1 full batch."""
    l1, _ = run_engine(0, {}, steps=4, gas=1)
    # same total batch via 2 micro steps: feed the same data
    groups.destroy_mesh()
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 0},
        "mesh": {"data_parallel_size": 8},
    }
    model = SimpleModel(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    batches = random_dataloader(None, 16 * 4, HIDDEN, batch_size=16)
    l2 = []
    for x, y in batches:
        halves = [(x[:8], y[:8]), (x[8:], y[8:])]
        step_losses = []
        for hx, hy in halves:
            loss = engine(hx, hy)
            engine.backward(loss)
            step_losses.append(float(loss))
        engine.step()
        l2.append(float(np.mean(step_losses)))
    assert np.allclose(l1, l2, rtol=1e-4, atol=1e-4), f"{l1} vs {l2}"


@pytest.mark.parametrize("opt", ["Lamb", "Lion", "Adagrad", "SGD"])
def test_other_optimizers_train(opt):
    losses, _ = run_engine(2, {"bf16": {"enabled": True}}, steps=5, opt=opt, lr=1e-3)
    assert losses[-1] < losses[0], f"{opt} failed to reduce loss: {losses}"


def test_fp32_stage0_tied_buffers():
    """fp32 + stage 0: master IS params (one donated buffer) — must not crash."""
    losses, engine = run_engine(0, {}, steps=3)
    assert engine.master_params is engine.params
    assert losses[-1] < losses[0]


# ----------------------------------------------------------------------
# Reference-style edge coverage (VERDICT weak #7): frozen params,
# unbalanced gradients, GatheredParameters write-back.
# ----------------------------------------------------------------------
class UnbalancedModel(nn.Module):
    """A branch whose output is masked out of the loss: its grads are
    exactly zero every step (reference TestZeroUnbalancedGradients)."""
    hidden_dim: int

    @nn.compact
    def __call__(self, x, y):
        h = nn.Dense(self.hidden_dim, name="used")(x)
        dead = nn.Dense(self.hidden_dim, name="unused_branch")(x)
        h = h + dead * 0.0
        logits = nn.Dense(self.hidden_dim, name="classifier")(h)
        labels = y.astype(jnp.int32)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.take_along_axis(logp, labels[..., None], axis=-1).mean()


@pytest.mark.parametrize("stage", [1, 3])
def test_unbalanced_gradients(stage):
    """Zero-grad branches must not break any stage, and trajectories
    must match the DP (stage 0) baseline."""
    def run(s):
        groups.destroy_mesh()
        cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "zero_optimization": {"stage": s}, "mesh": {"data_parallel_size": 8}}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=UnbalancedModel(hidden_dim=HIDDEN), config=cfg)
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        out = []
        for _ in range(4):
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
            out.append(float(loss))
        return out, engine

    base, _ = run(0)
    got, engine = run(stage)
    assert np.allclose(base, got, rtol=1e-5, atol=1e-6), f"{base} vs {got}"


def test_frozen_parameters_not_updated():
    groups.destroy_mesh()
    cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 2}, "mesh": {"data_parallel_size": 8},
           "frozen_parameters": ["linear_0"]}
    model = SimpleModel(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
    loss0 = engine(x, y)
    engine.backward(loss0)
    frozen_before = np.asarray(jax.device_get(engine.params["linear_0"]["kernel"]), np.float32)
    other_before = np.asarray(jax.device_get(engine.params["classifier"]["kernel"]), np.float32)
    engine.step()
    for _ in range(2):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
    frozen_after = np.asarray(jax.device_get(engine.params["linear_0"]["kernel"]), np.float32)
    other_after = np.asarray(jax.device_get(engine.params["classifier"]["kernel"]), np.float32)
    assert np.array_equal(frozen_before, frozen_after), "frozen param moved"
    assert not np.array_equal(other_before, other_after), "trainable param did not move"
    # exclude_frozen_parameters drops the frozen subtree
    sd = engine.module_state_dict(exclude_frozen_parameters=True)
    assert "linear_0" not in sd
    assert "classifier" in sd


def test_gathered_parameters_roundtrip_writeback():
    """Gather → modify → exit re-partitions onto the original shardings
    (reference GatheredParameters with modifier_rank)."""
    from deepspeed_tpu.runtime.zero import GatheredParameters
    groups.destroy_mesh()
    cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
           "mesh": {"data_parallel_size": 8}}
    model = SimpleModel(hidden_dim=HIDDEN, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
    engine(x, y)

    orig_sharding = engine.params["linear_0"]["kernel"].sharding
    with GatheredParameters(engine.params, engine=engine) as full:
        k = full["linear_0"]["kernel"]
        # gathered values are fully replicated: every shard sees the
        # whole array
        assert all(np.asarray(s.data).shape == k.shape for s in k.addressable_shards)
        full["linear_0"]["kernel"] = jnp.zeros_like(k)
    got = engine.params["linear_0"]["kernel"]
    assert got.sharding == orig_sharding, "write-back lost the zero sharding"
    assert float(jnp.abs(got).max()) == 0.0, "modification was not written back"
    # the fp32 master was updated too: the surgery must SURVIVE a step
    # (a stale master would revert the params on the next update)
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    after = np.asarray(jax.device_get(engine.params["linear_0"]["kernel"]), np.float32)
    assert np.abs(after).max() < 0.05, "stale master reverted the surgery"
    assert np.isfinite(float(loss))


def test_frozen_parameters_with_offload_optimizer():
    """Frozen subsets train under ZeRO-Offload (reference stage_1_and_2
    partitions only trainable params): the host SIMD update skips frozen
    leaves, which match the non-offload frozen run exactly."""
    groups.destroy_mesh()

    def run(offload):
        groups.destroy_mesh()
        cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "zero_optimization": {"stage": 2 if not offload else 3},
               "frozen_parameters": ["linear_0"]}
        if offload:
            cfg["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
        model = SimpleModel(hidden_dim=HIDDEN, nlayers=2)
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        losses = []
        for _ in range(3):
            loss = engine(x, y)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        return engine, losses

    base_engine, base = run(False)
    off_engine, off = run(True)
    np.testing.assert_allclose(base, off, rtol=2e-2)
    frozen0 = np.asarray(jax.device_get(base_engine.params["linear_0"]["kernel"]), np.float32)
    frozen1 = np.asarray(jax.device_get(off_engine.params["linear_0"]["kernel"]), np.float32)
    np.testing.assert_allclose(frozen0, frozen1, rtol=1e-6)  # both untouched inits
    # trainable leaves moved under offload too
    t0 = np.asarray(jax.device_get(off_engine.params["classifier"]["kernel"]), np.float32)
    loss = off_engine(*random_dataloader(None, 8, HIDDEN, batch_size=8)[0])
    off_engine.backward(loss)
    off_engine.step()
    t1 = np.asarray(jax.device_get(off_engine.params["classifier"]["kernel"]), np.float32)
    assert not np.array_equal(t0, t1)
