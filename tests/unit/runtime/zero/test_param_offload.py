"""ZeRO-Infinity parameter offload (zero_optimization.offload_param).

Reference match: ``deepspeed/runtime/zero/stage3.py`` offload branches +
``tests/unit/runtime/zero/test_zero_offloadpp.py`` style. TPU mechanism
under test: scanned-layer params live in the device's pinned_host
memory space and are streamed to HBM per layer inside the scan
(``runtime/zero/param_stream.py``)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import build_gpt, build_llama


def _cfg(**zero_extra):
    return {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 2,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0,
                              **zero_extra},
    }


def _ids(B=16, S=32, seed=0):
    return np.random.RandomState(seed).randint(0, 256, size=(B, S)).astype(np.int32)


class TestParamOffload:

    def test_layers_live_on_host_and_loss_matches(self):
        """Offloaded run: scanned-layer leaves in pinned_host, embeddings
        on device, loss trajectory identical to the non-offloaded run."""
        ids = _ids()

        def run(offload):
            from deepspeed_tpu.parallel import groups
            groups.destroy_mesh()
            extra = {"offload_param": {"device": "cpu"}} if offload else {}
            engine, _, _, _ = deepspeed_tpu.initialize(model=build_llama("debug"),
                                                       config=_cfg(**extra))
            losses = [float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
                      for _ in range(3)]
            return engine, losses

        _, base = run(False)
        engine, offl = run(True)
        k = engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert k.sharding.memory_kind == "pinned_host"
        assert engine.params["model"]["embed_tokens"].sharding.memory_kind == "device"
        np.testing.assert_allclose(base, offl, rtol=2e-2)
        assert offl[-1] < offl[0]

    def test_separate_step_path_keeps_host_residency(self):
        model = build_llama("debug")
        cfg = _cfg(offload_param={"device": "cpu"})
        cfg["train_micro_batch_size_per_gpu"] = 16
        cfg["gradient_accumulation_steps"] = 1
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
        ids = _ids()
        loss = engine(jnp.asarray(ids), jnp.asarray(ids))
        engine.backward(loss)
        engine.step()
        k = engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert k.sharding.memory_kind == "pinned_host"

    def test_gpt_family_offload(self):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_gpt("gpt2-debug"), config=_cfg(offload_param={"device": "cpu"}))
        ids = _ids()
        loss = float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
        assert np.isfinite(loss)
        k = engine.params["model"]["layers"]["attn"]["q_proj"]["kernel"]
        assert k.sharding.memory_kind == "pinned_host"

    def test_composes_with_optimizer_offload(self):
        """ZeRO-Infinity: params in pinned_host AND fp32 master/moments
        on the host optimizer — nothing persistent in HBM but
        embeddings."""
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_llama("debug"),
            config=_cfg(offload_param={"device": "cpu"},
                        offload_optimizer={"device": "cpu"}))
        ids = _ids()
        losses = [float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
                  for _ in range(3)]
        assert all(np.isfinite(l) for l in losses) and losses[-1] < losses[0]
        k = engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert k.sharding.memory_kind == "pinned_host"
        assert engine.opt_state is None  # optimizer state is host-resident

    def test_hybrid_engine_generate_streams_in_decode(self):
        """RLHF rollout on offloaded params: the decode scan streams layer
        slices too (ZeRO-Inference), so generate() works mid-training."""
        cfg = _cfg(offload_param={"device": "cpu"})
        cfg["hybrid_engine"] = {"enabled": True}
        engine, _, _, _ = deepspeed_tpu.initialize(model=build_llama("debug"), config=cfg)
        ids = _ids()
        engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))
        out = engine.generate(ids[:, :8], max_new_tokens=4)
        assert out.shape == (16, 12)
        k = engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert k.sharding.memory_kind == "pinned_host"

    def test_stage_below_3_raises(self):
        cfg = _cfg(offload_param={"device": "cpu"})
        cfg["zero_optimization"]["stage"] = 2
        engine, _, _, _ = deepspeed_tpu.initialize(model=build_llama("debug"), config=cfg)
        ids = _ids()
        with pytest.raises(ValueError, match="requires stage 3"):
            engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))

    def test_nvme_param_offload_trains_and_matches_cpu_offload(self, tmp_path):
        """Full ZeRO-Infinity param path: between steps the scanned-layer
        leaves are NVMe-file handles (no array storage), restored through
        pinned_host ahead of each dispatch; loss trajectory identical to
        the pinned_host-resident run (reference
        partitioned_param_swapper.py:36)."""
        from deepspeed_tpu.runtime.swap_tensor.param_swapper import NVMeParamHandle
        ids = _ids()

        def run(extra):
            from deepspeed_tpu.parallel import groups
            groups.destroy_mesh()
            engine, _, _, _ = deepspeed_tpu.initialize(model=build_llama("debug"),
                                                       config=_cfg(**extra))
            losses = [float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
                      for _ in range(3)]
            return engine, losses

        _, cpu_losses = run({"offload_param": {"device": "cpu"}})
        engine, nvme_losses = run({"offload_param": {"device": "nvme",
                                                     "nvme_path": str(tmp_path)}})
        np.testing.assert_allclose(cpu_losses, nvme_losses, rtol=1e-6)
        # between steps the streamed subtree really is swapped out
        k = engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert isinstance(k, NVMeParamHandle)
        assert engine._param_swapper.bytes_on_nvme() > 0
        # embeddings stay device-resident
        assert engine.params["model"]["embed_tokens"].sharding.memory_kind == "device"
        # a later step restores and re-offloads transparently
        l4 = float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
        assert np.isfinite(l4) and l4 < nvme_losses[0]
        assert isinstance(engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"],
                          NVMeParamHandle)

    def test_nvme_param_offload_checkpoint_and_generate(self, tmp_path):
        """save_checkpoint and hybrid generate restore swapped leaves on
        demand; separate fwd/bwd/step path keeps the swap cycle."""
        from deepspeed_tpu.runtime.swap_tensor.param_swapper import NVMeParamHandle
        cfg = _cfg(offload_param={"device": "nvme", "nvme_path": str(tmp_path / "swap")})
        cfg["hybrid_engine"] = {"enabled": True}
        cfg["train_micro_batch_size_per_gpu"] = 16
        cfg["gradient_accumulation_steps"] = 1
        engine, _, _, _ = deepspeed_tpu.initialize(model=build_llama("debug"), config=cfg)
        ids = _ids()
        loss = engine(jnp.asarray(ids), jnp.asarray(ids))
        engine.backward(loss)
        engine.step()
        assert isinstance(engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"],
                          NVMeParamHandle)
        out = engine.generate(ids[:, :8], max_new_tokens=4)
        assert out.shape == (16, 12)
        engine.save_checkpoint(str(tmp_path / "ckpt"), tag="t0")
        # another full step after checkpoint/generate restores cleanly
        loss2 = float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
        assert np.isfinite(loss2)

    def test_nvme_param_requires_path(self):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=build_llama("debug"),
            config=_cfg(offload_param={"device": "nvme"}))
        ids = _ids()
        with pytest.raises(AssertionError, match="nvme_path"):
            engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))

    def test_pipeline_engine_rejects_param_offload(self):
        from deepspeed_tpu.models.llama_pipe import build_llama_pipeline
        cfg = _cfg(offload_param={"device": "cpu"})
        cfg["mesh"] = {"pipeline_parallel_size": 2}
        cfg["train_micro_batch_size_per_gpu"] = 4
        cfg["train_batch_size"] = 8
        model = build_llama_pipeline("debug", num_stages=2, num_hidden_layers=4)
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
        ids = _ids(B=8)
        with pytest.raises(NotImplementedError, match="pipeline"):
            engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))

    def test_composes_with_quantized_comm(self):
        """offload_param × ZeRO++ quantized collectives (reference:
        stage3 offload + coalesced_collectives.py:31): the step hops the
        pinned_host tree to HBM before the manual shard_map region, so
        the int8 gather/reduce run on device operands. Loss parity vs
        the unquantized offload run."""
        from deepspeed_tpu.parallel import groups
        ids = _ids()

        def run(**zextra):
            groups.destroy_mesh()
            cfg = _cfg(offload_param={"device": "cpu"}, **zextra)
            cfg["mesh"] = {"data_parallel_size": 8}
            cfg["train_micro_batch_size_per_gpu"] = 16
            cfg["gradient_accumulation_steps"] = 1
            engine, _, _, _ = deepspeed_tpu.initialize(model=build_llama("debug"),
                                                       config=cfg)
            losses = [float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
                      for _ in range(3)]
            k = engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
            assert k.sharding.memory_kind == "pinned_host"
            groups.destroy_mesh()
            return losses

        base = run()
        quant = run(zero_quantized_weights=True, zero_quantized_gradients=True)
        np.testing.assert_allclose(base, quant, rtol=5e-2)
        assert quant[-1] < quant[0]

    def test_composes_with_onebit_adam(self):
        """offload_param × 1-bit Adam's compressed stage (reference:
        fp16/onebit/adam.py over runtime/comm): trains through the
        sign-compressed allreduce with host-resident params."""
        from deepspeed_tpu.parallel import groups
        groups.destroy_mesh()
        cfg = _cfg(offload_param={"device": "cpu"})
        cfg["optimizer"] = {"type": "OnebitAdam",
                            "params": {"lr": 1e-3, "freeze_step": 2}}
        cfg["mesh"] = {"data_parallel_size": 8}
        cfg["train_micro_batch_size_per_gpu"] = 16
        cfg["gradient_accumulation_steps"] = 1
        engine, _, _, _ = deepspeed_tpu.initialize(model=build_llama("debug"), config=cfg)
        ids = _ids()
        losses = [float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
                  for _ in range(4)]  # steps 3-4 run the compressed stage
        assert engine._use_compressed_now()
        k = engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert k.sharding.memory_kind == "pinned_host"
        assert losses[-1] < losses[0]
        assert all(np.isfinite(losses))
        groups.destroy_mesh()

    def test_arbitrary_flax_module_offloads(self):
        """Generic offload_param (reference parity: zero.Init wraps ANY
        nn.Module, partition_parameters.py:808): a plain flax model not
        from deepspeed_tpu.models trains with its whole param tree in
        pinned_host between steps — the jitted step uploads it — and the
        loss trajectory matches the non-offloaded run."""
        import flax.linen as nn

        class Plain(nn.Module):
            @nn.compact
            def __call__(self, x, y):
                h = nn.gelu(nn.Dense(64, name="up")(x))
                logits = nn.Dense(32, name="head")(h)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                return -jnp.take_along_axis(logp, y.astype(jnp.int32)[..., None], -1).mean()

        x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 32, 16)
        batch = ((jnp.asarray(x), jnp.asarray(y)), {})

        def run(offload):
            from deepspeed_tpu.parallel import groups
            groups.destroy_mesh()
            extra = {"offload_param": {"device": "cpu"}} if offload else {}
            engine, _, _, _ = deepspeed_tpu.initialize(model=Plain(), config=_cfg(**extra))
            losses = [float(engine.train_batch(batch=batch)) for _ in range(3)]
            return engine, losses

        _, base = run(False)
        engine, offl = run(True)
        for leaf in jax.tree.leaves(engine.params):
            assert leaf.sharding.memory_kind == "pinned_host"
        np.testing.assert_allclose(base, offl, rtol=2e-2)
        assert offl[-1] < offl[0]

    def test_arbitrary_module_offload_with_quantized_comm(self):
        """Generic (non-streaming) offload through the MANUAL quantized
        comm core: the pre-region hop must be the only upload — a second
        device_put inside the shard_map region would be illegal."""
        import flax.linen as nn
        from deepspeed_tpu.parallel import groups

        class Plain(nn.Module):
            @nn.compact
            def __call__(self, x, y):
                h = nn.gelu(nn.Dense(64, name="up")(x))
                logits = nn.Dense(32, name="head")(h)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                return -jnp.take_along_axis(logp, y.astype(jnp.int32)[..., None], -1).mean()

        groups.destroy_mesh()
        cfg = _cfg(offload_param={"device": "cpu"},
                   zero_quantized_weights=True, zero_quantized_gradients=True)
        cfg["mesh"] = {"data_parallel_size": 8}
        cfg["train_micro_batch_size_per_gpu"] = 16
        cfg["gradient_accumulation_steps"] = 1
        engine, _, _, _ = deepspeed_tpu.initialize(model=Plain(), config=cfg)
        x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 32, 16)
        batch = ((jnp.asarray(x), jnp.asarray(y)), {})
        losses = [float(engine.train_batch(batch=batch)) for _ in range(3)]
        for leaf in jax.tree.leaves(engine.params):
            assert leaf.sharding.memory_kind == "pinned_host"
        assert losses[-1] < losses[0] and all(np.isfinite(losses))
        groups.destroy_mesh()
