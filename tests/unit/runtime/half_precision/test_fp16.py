"""fp16 loss-scaling tests (analogue of reference tests/unit/runtime/half_precision/test_fp16.py)."""

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.runtime.fp16.loss_scaler import DynamicLossScaler, scaler_state, update_scale
from unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 32


def make_engine(fp16_cfg, lr=1e-3):
    groups.destroy_mesh()
    config = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": lr}},
        "fp16": fp16_cfg,
        "mesh": {"data_parallel_size": 8},
    }
    model = SimpleModel(hidden_dim=HIDDEN)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine


def test_fp16_trains():
    engine = make_engine({"enabled": True, "initial_scale_power": 8})
    losses = []
    for x, y in random_dataloader(None, 48, HIDDEN, batch_size=8):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_overflow_skips_step_and_halves_scale():
    engine = make_engine({"enabled": True, "initial_scale_power": 8, "hysteresis": 2})
    x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    params_before = engine.module_state_dict()
    scale_before = engine.get_loss_scale()

    # Poison a batch to force inf grads
    x_bad = x.copy()
    x_bad[0, 0] = np.inf

    # first overflow: hysteresis=2 absorbs it (reference loss_scaler.py
    # semantics), scale unchanged, step skipped
    loss = engine(x_bad, y)
    engine.backward(loss)
    engine.step()
    assert engine.overflow, "overflow was not detected"
    assert engine.skipped_steps == 1
    assert engine.get_loss_scale() == scale_before

    # second overflow: scale halves
    loss = engine(x_bad, y)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 2
    assert engine.get_loss_scale() == scale_before / 2

    params_after = engine.module_state_dict()
    import jax
    for a, b in zip(jax.tree.leaves(params_before), jax.tree.leaves(params_after)):
        assert np.array_equal(np.asarray(a, np.float32), np.asarray(b, np.float32)), \
            "params changed despite overflow"


def test_static_loss_scale():
    engine = make_engine({"enabled": True, "loss_scale": 128.0})
    assert engine.get_loss_scale() == 128.0
    x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    assert engine.get_loss_scale() == 128.0  # static: never changes


class TestDynamicScalerUnit:
    """Pure-function scaler semantics (window growth, hysteresis)."""

    def test_grow_after_window(self):
        st = scaler_state(init_scale=256.0)
        kw = dict(scale_window=4, min_scale=1.0, delayed_shift=1, consecutive_hysteresis=False, dynamic=True)
        for _ in range(4):
            st = update_scale(st, jnp.zeros((), bool), **kw)
        assert float(st["cur_scale"]) == 512.0

    def test_shrink_on_overflow(self):
        st = scaler_state(init_scale=256.0)
        kw = dict(scale_window=1000, min_scale=1.0, delayed_shift=1, consecutive_hysteresis=False, dynamic=True)
        st = update_scale(st, jnp.ones((), bool), **kw)
        assert float(st["cur_scale"]) == 128.0

    def test_hysteresis_delays_shrink(self):
        st = scaler_state(init_scale=256.0, delayed_shift=2)
        kw = dict(scale_window=1000, min_scale=1.0, delayed_shift=2, consecutive_hysteresis=False, dynamic=True)
        st = update_scale(st, jnp.ones((), bool), **kw)
        assert float(st["cur_scale"]) == 256.0  # first overflow burns hysteresis
        st = update_scale(st, jnp.ones((), bool), **kw)
        assert float(st["cur_scale"]) == 128.0

    def test_min_scale_floor(self):
        st = scaler_state(init_scale=2.0)
        kw = dict(scale_window=1000, min_scale=1.0, delayed_shift=1, consecutive_hysteresis=False, dynamic=True)
        st = update_scale(st, jnp.ones((), bool), **kw)
        st = update_scale(st, jnp.ones((), bool), **kw)
        assert float(st["cur_scale"]) == 1.0

    def test_host_mirror_matches(self):
        host = DynamicLossScaler(init_scale=256.0, scale_window=4, delayed_shift=1)
        st = scaler_state(init_scale=256.0)
        kw = dict(scale_window=4, min_scale=1, delayed_shift=1, consecutive_hysteresis=False, dynamic=True)
        pattern = [False, False, True, False, False, False, False, True]
        for ov in pattern:
            host.update_scale(ov)
            st = update_scale(st, jnp.asarray(ov), **kw)
        assert float(st["cur_scale"]) == host.cur_scale


def test_bf16_no_loss_scaling():
    groups.destroy_mesh()
    config = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 8,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "mesh": {"data_parallel_size": 8},
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=HIDDEN), config=config)
    assert engine.get_loss_scale() == 1.0
