"""Monitor backends (analogue of reference tests/unit/monitor/)."""

import csv
import os

from deepspeed_tpu.monitor.config import DeepSpeedMonitorConfig
from deepspeed_tpu.monitor.monitor import MonitorMaster, csvMonitor


def test_csv_monitor_writes_per_tag_files(tmp_path):
    cfg = DeepSpeedMonitorConfig(**{"csv_monitor": {
        "enabled": True, "output_path": str(tmp_path), "job_name": "job"}})
    mon = csvMonitor(cfg.csv_monitor)
    mon.write_events([("Train/loss", 1.5, 0), ("Train/loss", 1.2, 1),
                      ("Train/lr", 0.1, 0)])
    loss_file = tmp_path / "job" / "Train_loss.csv"
    assert loss_file.exists()
    rows = list(csv.reader(open(loss_file)))
    assert rows[0] == ["step", "loss"]  # header = last tag component
    assert rows[1] == ["0", "1.5"] and rows[2] == ["1", "1.2"]
    assert (tmp_path / "job" / "Train_lr.csv").exists()


def test_master_fans_out_to_enabled_backends(tmp_path):
    cfg = DeepSpeedMonitorConfig(**{"csv_monitor": {
        "enabled": True, "output_path": str(tmp_path), "job_name": "j2"}})
    master = MonitorMaster(cfg)
    assert master.enabled
    assert len(master.backends) == 1  # only csv enabled
    master.write_events([("x", 3.0, 7)])
    assert os.path.exists(tmp_path / "j2" / "x.csv")


def test_disabled_master_is_noop(tmp_path):
    master = MonitorMaster(DeepSpeedMonitorConfig())
    assert not master.enabled
    master.write_events([("x", 1.0, 0)])  # no crash, nothing written
