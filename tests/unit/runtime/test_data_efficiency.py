"""Data-efficiency tests (analogue of reference
tests/unit/runtime/test_data_efficiency.py: curriculum schedules,
curriculum sampler, random-LTD)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler, DeepSpeedDataSampler,
                                                 RandomLTDScheduler, apply_random_ltd)
from unit.simple_model import SimpleModel, random_dataloader


class TestCurriculumScheduler:

    def test_fixed_linear(self):
        s = CurriculumScheduler({"curriculum_type": "fixed_linear", "min_difficulty": 8,
                                 "max_difficulty": 64,
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8}})
        assert s.get_difficulty(0) == 8
        assert s.get_difficulty(50) == 32  # halfway, snapped to 8
        assert s.get_difficulty(100) == 64
        assert s.get_difficulty(10**6) == 64

    def test_fixed_root(self):
        s = CurriculumScheduler({"curriculum_type": "fixed_root", "min_difficulty": 8,
                                 "max_difficulty": 72,
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8,
                                                     "root_degree": 2}})
        # sqrt schedule front-loads difficulty growth
        assert s.get_difficulty(25) >= 8 + (72 - 8) // 4
        assert s.get_difficulty(100) == 72

    def test_fixed_discrete(self):
        s = CurriculumScheduler({"curriculum_type": "fixed_discrete", "min_difficulty": 2,
                                 "max_difficulty": 10,
                                 "schedule_config": {"difficulty": [2, 4, 10],
                                                     "max_step": [5, 10]}})
        assert s.get_difficulty(3) == 2
        assert s.get_difficulty(7) == 4
        assert s.get_difficulty(50) == 10

    def test_missing_keys_raise(self):
        with pytest.raises(ValueError):
            CurriculumScheduler({"curriculum_type": "fixed_linear"})


class TestDataSampler:

    def test_pool_widens_with_difficulty(self):
        diffs = np.arange(100, dtype=np.float64)  # sample i has difficulty i
        sampler = DeepSpeedDataSampler(
            100, batch_size=4, difficulties=diffs,
            curriculum_config={"curriculum_type": "fixed_linear", "min_difficulty": 10,
                               "max_difficulty": 100,
                               "schedule_config": {"total_curriculum_step": 10,
                                                   "difficulty_step": 10}})
        early = sampler.next_batch()
        assert early.max() <= 10  # only the easy prefix is admitted
        for _ in range(20):
            late = sampler.next_batch()
        assert late.max() > 10  # pool widened


class TestRandomLTD:

    def test_scheduler_anneals(self):
        s = RandomLTDScheduler(max_value=128, min_value=32, schedule_steps=100, step_size=16)
        assert s.get_seq(0) == 32
        assert s.get_seq(100) == 128
        assert 32 < s.get_seq(50) < 128

    def test_apply_preserves_dropped_tokens(self):
        rng = jax.random.PRNGKey(0)
        h = jnp.asarray(np.random.RandomState(0).randn(2, 16, 8), jnp.float32)
        marker = lambda x, pos: x + 100.0
        out = apply_random_ltd(marker, h, rng, keep=4)
        changed = np.isclose(np.asarray(out - h), 100.0).all(axis=(0, 2))
        assert changed.sum() == 4  # exactly `keep` positions went through the layer
        untouched = np.asarray(out - h)[:, ~changed, :]
        assert np.abs(untouched).max() == 0.0

    def test_keep_all_is_identity_path(self):
        rng = jax.random.PRNGKey(0)
        h = jnp.ones((1, 8, 4))
        out = apply_random_ltd(lambda x, p: x * 2, h, rng, keep=8)
        assert np.allclose(np.asarray(out), 2.0)


class TestEngineCurriculum:

    def test_legacy_curriculum_truncates_seqlen(self):
        import flax.linen as nn

        class SeqModel(nn.Module):
            @nn.compact
            def __call__(self, ids, labels):
                emb = nn.Embed(64, 16)(ids)
                logits = nn.Dense(64)(emb)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                return -jnp.take_along_axis(logp, labels.astype(jnp.int32)[..., None], -1).mean()

        groups.destroy_mesh()
        seen = []

        config = {
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "mesh": {"data_parallel_size": 8},
            "curriculum_learning": {"enabled": True, "curriculum_type": "fixed_linear",
                                    "min_difficulty": 8, "max_difficulty": 32,
                                    "schedule_config": {"total_curriculum_step": 4,
                                                        "difficulty_step": 8}},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=SeqModel(), config=config)
        ids = np.zeros((8, 32), np.int32)
        for step in range(5):
            engine.train_batch(batch=(ids, ids))
            seen.append(engine.curriculum_scheduler_legacy.current_difficulty)
        assert seen[0] == 8
        assert seen[-1] == 32
        assert seen == sorted(seen)


class TestDataAnalyzer:

    def test_map_reduce_and_sampler_integration(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling.data_analyzer import DataAnalyzer
        data = [list(range(n)) for n in [5, 2, 9, 1, 7, 3, 8, 6]]  # "difficulty" = length
        # two workers analyze disjoint strides
        for w in range(2):
            DataAnalyzer(data, metric_names=["seqlen"],
                         metric_functions=[len],
                         save_path=str(tmp_path), num_workers=2, worker_id=w).run_map()
        summary = DataAnalyzer(data, metric_names=["seqlen"], metric_functions=[len],
                               save_path=str(tmp_path), num_workers=2).run_reduce()
        assert summary["seqlen"]["min"] == 1 and summary["seqlen"]["max"] == 9

        metrics = DataAnalyzer.load_index_to_metric(str(tmp_path), "seqlen")
        assert list(metrics) == [5, 2, 9, 1, 7, 3, 8, 6]
        order = np.load(tmp_path / "seqlen_metric_to_sample.npy")
        assert list(metrics[order]) == sorted(metrics)

        # feeds the curriculum sampler directly
        sampler = DeepSpeedDataSampler(
            len(data), batch_size=2, difficulties=metrics,
            curriculum_config={"curriculum_type": "fixed_linear", "min_difficulty": 2,
                               "max_difficulty": 9,
                               "schedule_config": {"total_curriculum_step": 4,
                                                   "difficulty_step": 1}})
        first = sampler.next_batch()
        assert all(metrics[i] <= 2 for i in first)

    def test_reduce_detects_missing_worker(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling.data_analyzer import DataAnalyzer
        data = [[0]] * 6
        DataAnalyzer(data, metric_names=["m"], metric_functions=[len],
                     save_path=str(tmp_path), num_workers=2, worker_id=0).run_map()
        with pytest.raises((RuntimeError, FileNotFoundError)):
            DataAnalyzer(data, metric_names=["m"], metric_functions=[len],
                         save_path=str(tmp_path), num_workers=2).run_reduce()


class TestMMapIndexedDataset:

    def test_build_and_mmap_read(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
            MMapIndexedDataset, MMapIndexedDatasetBuilder)
        prefix = str(tmp_path / "corpus")
        rng = np.random.RandomState(0)
        samples = [rng.randint(0, 1000, size=rng.randint(3, 40)).astype(np.int32)
                   for _ in range(50)]
        builder = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
        for s in samples:
            builder.add_item(s)
        builder.finalize()
        assert MMapIndexedDataset.exists(prefix)

        ds = MMapIndexedDataset(prefix)
        assert len(ds) == 50
        assert ds.dtype == np.int32
        for i in (0, 7, 49):
            np.testing.assert_array_equal(np.asarray(ds[i]), samples[i])
        # partial window read
        np.testing.assert_array_equal(np.asarray(ds.get(7, offset=1, length=2)),
                                      samples[7][1:3])
        # reads are memmap views, not RAM copies
        assert isinstance(ds[0].base, np.memmap) or isinstance(ds[0], np.memmap)
        np.testing.assert_array_equal(np.asarray(ds.sizes),
                                      [len(s) for s in samples])

    def test_reference_binary_layout(self, tmp_path):
        """The on-disk bytes follow the Megatron/DeepSpeed MMIDIDX layout
        (reference indexed_dataset.py) so existing corpora interchange."""
        from deepspeed_tpu.runtime.data_pipeline.data_sampling import \
            MMapIndexedDatasetBuilder
        prefix = str(tmp_path / "c")
        b = MMapIndexedDatasetBuilder(prefix, dtype=np.uint16)
        b.add_item([1, 2, 3])
        b.add_item([4, 5])
        b.finalize()
        raw = open(prefix + ".idx", "rb").read()
        assert raw[:9] == b"MMIDIDX\x00\x00"
        import struct
        version, = struct.unpack("<Q", raw[9:17])
        dtype_code = raw[17]
        n, = struct.unpack("<Q", raw[18:26])
        assert (version, dtype_code, n) == (1, 6, 2)  # 6 = uint16 (ref table)
        assert open(prefix + ".bin", "rb").read() == \
            np.asarray([1, 2, 3, 4, 5], np.uint16).tobytes()


class TestDistributedDataAnalyzer:

    def test_multiprocess_analysis_feeds_curriculum(self, tmp_path):
        """The reference pipeline end-to-end at scale semantics: build an
        on-disk indexed dataset, analyze it with MULTIPLE PROCESSES,
        feed the resulting mmap'd index->metric into
        DeepSpeedDataSampler for a curriculum run — the dataset is never
        resident in RAM."""
        from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
            DataAnalyzer, DeepSpeedDataSampler, DistributedDataAnalyzer,
            MMapIndexedDatasetBuilder)
        prefix = str(tmp_path / "corpus")
        rng = np.random.RandomState(1)
        lengths = rng.randint(4, 100, size=200)
        builder = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
        for n in lengths:
            builder.add_item(rng.randint(0, 500, size=n).astype(np.int32))
        builder.finalize()

        save = str(tmp_path / "analysis")
        dda = DistributedDataAnalyzer(dataset_prefix=prefix,
                                      metric_names=["seq_length"],
                                      metric_functions=["seq_length"],
                                      save_path=save, num_workers=2)
        summary = dda.run_map_reduce()
        assert summary["seq_length"]["min"] == float(lengths.min())
        assert summary["seq_length"]["max"] == float(lengths.max())

        metric = DataAnalyzer.load_index_to_metric(save, "seq_length")
        assert isinstance(metric, np.memmap)  # mmap'd, not loaded
        np.testing.assert_array_equal(np.asarray(metric), lengths.astype(np.float64))

        sampler = DeepSpeedDataSampler(
            total_samples=200, batch_size=8, difficulties=metric,
            curriculum_config={"curriculum_type": "fixed_linear",
                               "min_difficulty": 8, "max_difficulty": 100,
                               "schedule_config": {"total_curriculum_step": 20,
                                                   "difficulty_step": 1}})
        early = sampler.next_batch()
        for _ in range(25):
            late = sampler.next_batch()
        # the curriculum really gates on the analyzed metric
        assert lengths[early].max() <= 8
        assert lengths[late].max() > 8
