"""Data-efficiency tests (analogue of reference
tests/unit/runtime/test_data_efficiency.py: curriculum schedules,
curriculum sampler, random-LTD)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler, DeepSpeedDataSampler,
                                                 RandomLTDScheduler, apply_random_ltd)
from unit.simple_model import SimpleModel, random_dataloader


class TestCurriculumScheduler:

    def test_fixed_linear(self):
        s = CurriculumScheduler({"curriculum_type": "fixed_linear", "min_difficulty": 8,
                                 "max_difficulty": 64,
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8}})
        assert s.get_difficulty(0) == 8
        assert s.get_difficulty(50) == 32  # halfway, snapped to 8
        assert s.get_difficulty(100) == 64
        assert s.get_difficulty(10**6) == 64

    def test_fixed_root(self):
        s = CurriculumScheduler({"curriculum_type": "fixed_root", "min_difficulty": 8,
                                 "max_difficulty": 72,
                                 "schedule_config": {"total_curriculum_step": 100,
                                                     "difficulty_step": 8,
                                                     "root_degree": 2}})
        # sqrt schedule front-loads difficulty growth
        assert s.get_difficulty(25) >= 8 + (72 - 8) // 4
        assert s.get_difficulty(100) == 72

    def test_fixed_discrete(self):
        s = CurriculumScheduler({"curriculum_type": "fixed_discrete", "min_difficulty": 2,
                                 "max_difficulty": 10,
                                 "schedule_config": {"difficulty": [2, 4, 10],
                                                     "max_step": [5, 10]}})
        assert s.get_difficulty(3) == 2
        assert s.get_difficulty(7) == 4
        assert s.get_difficulty(50) == 10

    def test_missing_keys_raise(self):
        with pytest.raises(ValueError):
            CurriculumScheduler({"curriculum_type": "fixed_linear"})


class TestDataSampler:

    def test_pool_widens_with_difficulty(self):
        diffs = np.arange(100, dtype=np.float64)  # sample i has difficulty i
        sampler = DeepSpeedDataSampler(
            100, batch_size=4, difficulties=diffs,
            curriculum_config={"curriculum_type": "fixed_linear", "min_difficulty": 10,
                               "max_difficulty": 100,
                               "schedule_config": {"total_curriculum_step": 10,
                                                   "difficulty_step": 10}})
        early = sampler.next_batch()
        assert early.max() <= 10  # only the easy prefix is admitted
        for _ in range(20):
            late = sampler.next_batch()
        assert late.max() > 10  # pool widened


class TestRandomLTD:

    def test_scheduler_anneals(self):
        s = RandomLTDScheduler(max_value=128, min_value=32, schedule_steps=100, step_size=16)
        assert s.get_seq(0) == 32
        assert s.get_seq(100) == 128
        assert 32 < s.get_seq(50) < 128

    def test_apply_preserves_dropped_tokens(self):
        rng = jax.random.PRNGKey(0)
        h = jnp.asarray(np.random.RandomState(0).randn(2, 16, 8), jnp.float32)
        marker = lambda x, pos: x + 100.0
        out = apply_random_ltd(marker, h, rng, keep=4)
        changed = np.isclose(np.asarray(out - h), 100.0).all(axis=(0, 2))
        assert changed.sum() == 4  # exactly `keep` positions went through the layer
        untouched = np.asarray(out - h)[:, ~changed, :]
        assert np.abs(untouched).max() == 0.0

    def test_keep_all_is_identity_path(self):
        rng = jax.random.PRNGKey(0)
        h = jnp.ones((1, 8, 4))
        out = apply_random_ltd(lambda x, p: x * 2, h, rng, keep=8)
        assert np.allclose(np.asarray(out), 2.0)


class TestEngineCurriculum:

    def test_legacy_curriculum_truncates_seqlen(self):
        import flax.linen as nn

        class SeqModel(nn.Module):
            @nn.compact
            def __call__(self, ids, labels):
                emb = nn.Embed(64, 16)(ids)
                logits = nn.Dense(64)(emb)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                return -jnp.take_along_axis(logp, labels.astype(jnp.int32)[..., None], -1).mean()

        groups.destroy_mesh()
        seen = []

        config = {
            "train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "mesh": {"data_parallel_size": 8},
            "curriculum_learning": {"enabled": True, "curriculum_type": "fixed_linear",
                                    "min_difficulty": 8, "max_difficulty": 32,
                                    "schedule_config": {"total_curriculum_step": 4,
                                                        "difficulty_step": 8}},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=SeqModel(), config=config)
        ids = np.zeros((8, 32), np.int32)
        for step in range(5):
            engine.train_batch(batch=(ids, ids))
            seen.append(engine.curriculum_scheduler_legacy.current_difficulty)
        assert seen[0] == 8
        assert seen[-1] == 32
        assert seen == sorted(seen)


class TestDataAnalyzer:

    def test_map_reduce_and_sampler_integration(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling.data_analyzer import DataAnalyzer
        data = [list(range(n)) for n in [5, 2, 9, 1, 7, 3, 8, 6]]  # "difficulty" = length
        # two workers analyze disjoint strides
        for w in range(2):
            DataAnalyzer(data, metric_names=["seqlen"],
                         metric_functions=[len],
                         save_path=str(tmp_path), num_workers=2, worker_id=w).run_map()
        summary = DataAnalyzer(data, metric_names=["seqlen"], metric_functions=[len],
                               save_path=str(tmp_path), num_workers=2).run_reduce()
        assert summary["seqlen"]["min"] == 1 and summary["seqlen"]["max"] == 9

        metrics = DataAnalyzer.load_index_to_metric(str(tmp_path), "seqlen")
        assert list(metrics) == [5, 2, 9, 1, 7, 3, 8, 6]
        order = np.load(tmp_path / "seqlen_metric_to_sample.npy")
        assert list(metrics[order]) == sorted(metrics)

        # feeds the curriculum sampler directly
        sampler = DeepSpeedDataSampler(
            len(data), batch_size=2, difficulties=metrics,
            curriculum_config={"curriculum_type": "fixed_linear", "min_difficulty": 2,
                               "max_difficulty": 9,
                               "schedule_config": {"total_curriculum_step": 4,
                                                   "difficulty_step": 1}})
        first = sampler.next_batch()
        assert all(metrics[i] <= 2 for i in first)

    def test_reduce_detects_missing_worker(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline.data_sampling.data_analyzer import DataAnalyzer
        data = [[0]] * 6
        DataAnalyzer(data, metric_names=["m"], metric_functions=[len],
                     save_path=str(tmp_path), num_workers=2, worker_id=0).run_map()
        with pytest.raises((RuntimeError, FileNotFoundError)):
            DataAnalyzer(data, metric_names=["m"], metric_functions=[len],
                         save_path=str(tmp_path), num_workers=2).run_reduce()
