"""Utils-misc tests: OptimizedLinear/LoRA, activation checkpointing API,
tensor_fragment, init_on_device, z3 leaf, structural AutoTP."""

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 32


class TestOptimizedLinear:

    def test_lora_only_adapters_learn(self):
        from deepspeed_tpu.linear import LoRAConfig, OptimizedLinear, lora_frozen_patterns

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x, y):
                h = OptimizedLinear(output_dim=HIDDEN, dtype=jnp.float32,
                                    lora_config=LoRAConfig(lora_r=4), name="ol")(x)
                logp = jax.nn.log_softmax(h.astype(jnp.float32), -1)
                return -jnp.take_along_axis(logp, y.astype(jnp.int32)[..., None], -1).mean()

        groups.destroy_mesh()
        cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "mesh": {"data_parallel_size": 8},
               "frozen_parameters": lora_frozen_patterns()}
        engine, _, _, _ = deepspeed_tpu.initialize(model=Net(), config=cfg)
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        base0 = None
        losses = []
        for _ in range(4):
            loss = engine(x, y)
            engine.backward(loss)
            if base0 is None:
                base0 = np.asarray(jax.device_get(engine.params["ol"]["base_kernel"]))
            engine.step()
            losses.append(float(loss))
        base1 = np.asarray(jax.device_get(engine.params["ol"]["base_kernel"]))
        assert np.array_equal(base0, base1), "frozen base moved"
        assert losses[-1] < losses[0], losses
        b = np.asarray(jax.device_get(engine.params["ol"]["lora_b"]))
        assert np.abs(b).max() > 0, "lora_b never updated"

    def test_quantized_parameter_roundtrip(self):
        from deepspeed_tpu.linear import QuantizationConfig, QuantizedParameter
        rng = np.random.RandomState(0)
        w = rng.randn(64, 32).astype(np.float32)
        qp = QuantizedParameter(w, QuantizationConfig(group_size=128))
        back = np.asarray(qp.dequantized(jnp.float32))
        assert back.shape == w.shape
        assert np.abs(back - w).max() < np.abs(w).max() / 50


class TestActivationCheckpointingAPI:

    def test_checkpoint_matches_uncheckpointed(self):
        from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ckpt
        ckpt.configure(partition_activations=True)
        assert ckpt.is_configured()

        def block(x):
            return jnp.tanh(x @ jnp.ones((8, 8), jnp.float32)) * 2.0

        x = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)
        direct = jax.grad(lambda x: block(x).sum())(x)
        remat = jax.grad(lambda x: ckpt.checkpoint(block, x).sum())(x)
        np.testing.assert_allclose(np.asarray(direct), np.asarray(remat), rtol=1e-6)

    def test_rng_tracker(self):
        from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
            get_cuda_rng_tracker, model_parallel_cuda_manual_seed)
        model_parallel_cuda_manual_seed(1234)
        with get_cuda_rng_tracker().fork() as key:
            a = jax.random.normal(key, (4,))
        with get_cuda_rng_tracker().fork() as key:
            b = jax.random.normal(key, (4,))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # deterministic fork


class TestTensorFragment:

    def test_get_set_full_param_and_state(self):
        from deepspeed_tpu.utils.tensor_fragment import (safe_get_full_fp32_param,
                                                         safe_get_full_grad,
                                                         safe_get_full_optimizer_state,
                                                         safe_set_full_fp32_param)
        groups.destroy_mesh()
        cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "bf16": {"enabled": True}, "zero_optimization": {"stage": 3},
               "mesh": {"data_parallel_size": 8}}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=HIDDEN, nlayers=2), config=cfg)
        x, y = random_dataloader(None, 8, HIDDEN, batch_size=8)[0]
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()

        path = "linear_0/kernel"
        w = safe_get_full_fp32_param(engine, path)
        assert w.shape == (HIDDEN, HIDDEN) and w.dtype == np.float32
        m = safe_get_full_optimizer_state(engine, path, "exp_avg")
        assert m.shape == (HIDDEN, HIDDEN)
        loss = engine(x, y)
        engine.backward(loss)
        g = safe_get_full_grad(engine, path)
        assert g is not None and g.shape == (HIDDEN, HIDDEN)

        safe_set_full_fp32_param(engine, path, np.zeros((HIDDEN, HIDDEN), np.float32))
        assert np.abs(safe_get_full_fp32_param(engine, path)).max() == 0.0
        # compute-dtype copy refreshed as well
        assert float(jnp.abs(engine.params["linear_0"]["kernel"]).max()) == 0.0


class TestInitOnDevice:

    def test_meta_then_materialize_sharded(self):
        from deepspeed_tpu.utils.init_on_device import OnDevice
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        groups.destroy_mesh()
        mesh = groups.get_mesh()
        model = SimpleModel(hidden_dim=HIDDEN, nlayers=1)
        sample = (jnp.zeros((4, HIDDEN)), jnp.zeros(4, jnp.int32))
        with OnDevice(dtype=jnp.bfloat16) as od:
            abstract = od.abstract_init(model, *sample)
        leaf = abstract["linear_0"]["kernel"]
        assert isinstance(leaf, jax.ShapeDtypeStruct) and leaf.dtype == jnp.bfloat16

        shardings = jax.tree.map(lambda s: NamedSharding(mesh, P()), abstract)
        with OnDevice(dtype=jnp.bfloat16) as od:
            real = od.materialize(model, *sample, shardings=shardings)
        assert real["linear_0"]["kernel"].dtype == jnp.bfloat16


class TestZ3Leaf:

    def test_mark_and_query(self):
        from deepspeed_tpu.utils.z3_leaf_module import (set_z3_leaf_modules, unset_z3_leaf_modules,
                                                        z3_leaf_module)
        m = SimpleModel(hidden_dim=8)
        marked = set_z3_leaf_modules(m, [SimpleModel])
        assert marked and z3_leaf_module(m)
        unset_z3_leaf_modules(m, [SimpleModel])
        assert not z3_leaf_module(m)
        with pytest.raises(ValueError):
            set_z3_leaf_modules(m, [nn.Dense])


class TestStructuralAutoTP:

    def test_unconventionally_named_model_gets_tp(self):
        """VERDICT weak #6: a model with nonstandard names must still get
        a real TP layout from the structural parser."""
        from deepspeed_tpu.module_inject.auto_tp import AutoTP

        class Weird(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = nn.Dense(4 * HIDDEN, name="alpha")(x)      # up-ish
                h = nn.Dense(HIDDEN, name="beta")(nn.gelu(h))  # down-ish
                return h

        m = Weird()
        p = m.init(jax.random.PRNGKey(0), jnp.zeros((2, HIDDEN)))["params"]
        tp = AutoTP.tp_parser(params=p)
        up = tp("alpha/kernel", (HIDDEN, 4 * HIDDEN))
        down = tp("beta/kernel", (4 * HIDDEN, HIDDEN))
        assert tuple(up) == (None, "tensor"), up       # column-parallel
        assert tuple(down) == ("tensor", None), down   # row-parallel

    def test_square_falls_back_to_names(self):
        from deepspeed_tpu.module_inject.auto_tp import AutoTP
        p = {"attn": {"o_proj": {"kernel": jnp.zeros((HIDDEN, HIDDEN))}},
             "mlp": {"up": {"kernel": jnp.zeros((HIDDEN, 2 * HIDDEN))}}}
        tp = AutoTP.tp_parser(params=p)
        assert tuple(tp("attn/o_proj/kernel", (HIDDEN, HIDDEN))) == ("tensor", None)


class TestParityOdds:

    def test_nebula_config_parses(self):
        # nebula is live (round 7): enabling it configures the native
        # async checkpoint service instead of raising
        from deepspeed_tpu.nebula import get_nebula_config
        assert get_nebula_config({}).enabled is False
        cfg = get_nebula_config({"nebula": {"enabled": True,
                                            "persistent_storage_path": "/tmp/ckpt",
                                            "num_of_version_in_retention": 3}})
        assert cfg.enabled and cfg.num_of_version_in_retention == 3
        with pytest.raises(ValueError):
            get_nebula_config({"nebula": {"enabled": True,
                                          "num_of_version_in_retention": 0}})

    def test_numa_binding(self):
        from deepspeed_tpu.utils.numa import bind_rank_to_cores, get_numa_cores
        nodes = get_numa_cores()
        assert nodes and all(isinstance(c, int) for c in nodes[0])
        import os
        before = os.sched_getaffinity(0)
        mine = bind_rank_to_cores(0, 1)
        assert mine  # full-core slice for a single rank
        os.sched_setaffinity(0, before)  # restore

    def test_engine_compile_surface(self):
        groups.destroy_mesh()
        cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "mesh": {"data_parallel_size": 8}}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=SimpleModel(hidden_dim=HIDDEN, nlayers=1), config=cfg)
        assert not engine.is_compiled
        assert engine.compile() is engine
        assert engine.is_compiled
        with pytest.raises(ValueError):
            engine.compile(backend="tvm")
