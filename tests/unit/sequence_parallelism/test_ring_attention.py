"""Ring attention (context parallelism) tests.

Beyond-reference capability (the reference ships only Ulysses): ring
attention must match dense attention exactly, differentiate, and train
through the engine on a sequence-sharded mesh with the same loss
trajectory as Ulysses."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import build_llama
from deepspeed_tpu.models.llama import einsum_attention
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import make_mesh_topology
from deepspeed_tpu.sequence.ring_attention import ring_attention


def _qkv(B=2, S=32, H=4, D=16, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(B, S, H, D).astype(np.float32)) for _ in range(3))


class TestRingAttentionMath:

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        mesh = make_mesh_topology(sequence=4, data=2, devices=jax.devices())
        q, k, v = _qkv()
        out = ring_attention(q, k, v, causal=causal, mesh=mesh)
        ref = einsum_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_grad_matches_dense(self):
        mesh = make_mesh_topology(sequence=8, devices=jax.devices())
        q, k, v = _qkv(S=16)
        g = jax.grad(lambda q, k, v: (ring_attention(q, k, v, mesh=mesh) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
        gref = jax.grad(lambda q, k, v: (einsum_attention(q, k, v, causal=True) ** 2).sum(),
                        argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)

    def test_gqa_kv_travel_unexpanded(self):
        """K/V enter the ring with Hkv heads; expansion is shard-local."""
        mesh = make_mesh_topology(sequence=4, devices=jax.devices()[:4])
        rng = np.random.RandomState(1)
        B, S, H, Hkv, D = 2, 16, 4, 2, 8
        q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, S, Hkv, D).astype(np.float32))
        out = ring_attention(q, k, v, causal=True, mesh=mesh)
        kx = jnp.repeat(k, H // Hkv, axis=2)
        vx = jnp.repeat(v, H // Hkv, axis=2)
        ref = einsum_attention(q, kx, vx, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_single_device_axis_falls_back(self):
        mesh = make_mesh_topology(data=8, devices=jax.devices())
        q, k, v = _qkv()
        out = ring_attention(q, k, v, causal=True, mesh=mesh)
        ref = einsum_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


class TestRingInModel:

    def _train(self, sp_impl, ids):
        groups.destroy_mesh()
        model = build_llama("debug", sp_impl=sp_impl)
        config = {
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"sequence_parallel_size": 4, "data_parallel_size": 2},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        return [float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
                for _ in range(3)]

    def test_ring_trains_like_ulysses(self):
        """Same data, same init seed path: ring and Ulysses are two
        schedules for the same math — loss trajectories must agree."""
        ids = np.random.RandomState(0).randint(0, 256, size=(4, 32)).astype(np.int32)
        ul = self._train("ulysses", ids)
        ring = self._train("ring", ids)
        assert all(np.isfinite(l) for l in ring) and ring[-1] < ring[0]
        np.testing.assert_allclose(ring, ul, rtol=2e-3)

    def test_unknown_sp_impl_raises(self):
        ids = np.random.RandomState(0).randint(0, 256, size=(4, 32)).astype(np.int32)
        with pytest.raises(ValueError, match="sp_impl"):
            self._train("rings", ids)
