"""Ulysses sequence-parallel tests (analogue of reference
tests/unit/sequence_parallelism/test_ulysses.py)."""

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.llama import einsum_attention
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.sequence.layer import (DistributedAttention, constrain_hidden, head_to_seq_shard,
                                          seq_to_head_shard)


class TestUlyssesReshard:

    def test_seq_head_roundtrip_identity(self):
        groups.initialize_mesh({"sequence_parallel_size": 4})
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 8, 4))

        @jax.jit
        def roundtrip(x):
            return head_to_seq_shard(seq_to_head_shard(x))

        np.testing.assert_allclose(np.asarray(roundtrip(x)), np.asarray(x), rtol=1e-6)

    def test_head_shard_layout(self):
        groups.initialize_mesh({"sequence_parallel_size": 4})
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 8, 4))
        y = jax.jit(seq_to_head_shard)(x)
        spec = y.sharding.spec
        # heads dim (axis 2) carries the sequence axis; seq dim is unsharded
        assert "sequence" in str(spec[2])
        assert spec[1] is None

    def test_distributed_attention_matches_local(self):
        """Ulysses-wrapped attention == plain attention numerically."""
        groups.initialize_mesh({"sequence_parallel_size": 4})
        B, S, H, D = 2, 32, 8, 16
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q = jax.random.normal(ks[0], (B, S, H, D))
        k = jax.random.normal(ks[1], (B, S, H, D))
        v = jax.random.normal(ks[2], (B, S, H, D))

        dist_attn = DistributedAttention(einsum_attention)
        out_dist = jax.jit(lambda q, k, v: dist_attn(q, k, v, causal=True))(q, k, v)
        out_ref = einsum_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out_dist), np.asarray(out_ref), rtol=2e-5, atol=2e-5)

    def test_mixed_sp_tp_mesh(self):
        groups.initialize_mesh({"sequence_parallel_size": 2, "tensor_parallel_size": 2,
                                "data_parallel_size": 2})
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 8, 4))
        y = jax.jit(seq_to_head_shard)(x)
        # heads dim sharded over tensor AND sequence (4-way)
        assert y.sharding.shard_shape(y.shape)[2] == 2

    def test_constrain_hidden_noop_without_mesh(self):
        x = jnp.ones((2, 4, 8))
        assert constrain_hidden(x) is x
