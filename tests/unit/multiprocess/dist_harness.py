"""Multi-process test harness.

The analogue of the reference's ``tests/unit/common.py`` ``DistributedTest``
(spawns N ranks per test over torch.distributed): here each "host" is a
real OS process with its OWN set of virtual CPU devices, rendezvoused
through ``jax.distributed`` — the exact mechanism a multi-host TPU slice
uses — so host-plane logic (rendezvous, process-spanning meshes, sharded
checkpoint writes from several processes) runs for real.

Usage::

    result = run_distributed(worker_fn, world_size=2, devices_per_proc=4)

``worker_fn(rank, world_size)`` executes in a fresh process AFTER
jax.distributed initialization; its return value must be picklable.
"""

import multiprocessing as mp
import os
import socket
import sys
import traceback


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _entry(fn, rank, world, port, devices_per_proc, queue, extra_env):
    try:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count={devices_per_proc}")
        os.environ["JAX_PLATFORMS"] = "cpu"
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        os.environ["PYTHONPATH"] = os.pathsep.join(
            [repo, os.path.join(repo, "tests"), os.environ.get("PYTHONPATH", "")])
        sys.path.insert(0, repo)
        sys.path.insert(0, os.path.join(repo, "tests"))
        os.environ.update(extra_env or {})
        os.environ["MASTER_ADDR"] = "127.0.0.1"
        os.environ["MASTER_PORT"] = str(port)
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax
        jax.config.update("jax_platforms", "cpu")
        import deepspeed_tpu.comm as dist
        dist.init_distributed()
        out = fn(rank, world)
        queue.put((rank, "ok", out))
    except Exception:
        queue.put((rank, "error", traceback.format_exc()))


def run_distributed(fn, world_size=2, devices_per_proc=4, timeout=300, extra_env=None):
    """Spawn ``world_size`` processes, rendezvous them, run ``fn`` in
    each; → {rank: return value}. Raises with the failing rank's
    traceback on any error."""
    ctx = mp.get_context("spawn")
    queue = ctx.Queue()
    port = _free_port()
    procs = [ctx.Process(target=_entry,
                         args=(fn, r, world_size, port, devices_per_proc, queue, extra_env))
             for r in range(world_size)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(world_size):
            rank, status, payload = queue.get(timeout=timeout)
            if status == "error":
                raise RuntimeError(f"rank {rank} failed:\n{payload}")
            results[rank] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    return results
