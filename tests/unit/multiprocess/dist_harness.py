"""Multi-process test harness.

The analogue of the reference's ``tests/unit/common.py`` ``DistributedTest``
(spawns N ranks per test over torch.distributed): here each "host" is a
real OS process with its OWN set of virtual CPU devices, rendezvoused
through ``jax.distributed`` — the exact mechanism a multi-host TPU slice
uses — so host-plane logic (rendezvous, process-spanning meshes, sharded
checkpoint writes from several processes) runs for real.

Usage::

    result = run_distributed(worker_fn, world_size=2, devices_per_proc=4)

``worker_fn(rank, world_size)`` executes in a fresh process AFTER
jax.distributed initialization; its return value must be picklable.
"""

import multiprocessing as mp
import os
import socket
import traceback


def _free_port():
    # SO_REUSEADDR narrows (does not fully close — fail-fast polling in
    # run_distributed covers the rest) the TOCTOU window between this
    # close and the rank-0 coordinator's bind
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _entry(fn, rank, world, port, devices_per_proc, queue, extra_env):
    try:
        # (sys.path arrives from the parent via spawn's preparation data —
        # conftest already seeded the repo root and tests dir)
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count={devices_per_proc}")
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.update(extra_env or {})
        os.environ["MASTER_ADDR"] = "127.0.0.1"
        os.environ["MASTER_PORT"] = str(port)
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax
        jax.config.update("jax_platforms", "cpu")
        import deepspeed_tpu.comm as dist
        dist.init_distributed()
        out = fn(rank, world)
        queue.put((rank, "ok", out))
    except Exception:
        queue.put((rank, "error", traceback.format_exc()))


def run_distributed(fn, world_size=2, devices_per_proc=4, timeout=300, extra_env=None):
    """Spawn ``world_size`` processes, rendezvous them, run ``fn`` in
    each; → {rank: return value}. Raises with the failing rank's
    traceback on any error."""
    ctx = mp.get_context("spawn")
    queue = ctx.Queue()
    port = _free_port()
    procs = [ctx.Process(target=_entry,
                         args=(fn, r, world_size, port, devices_per_proc, queue, extra_env))
             for r in range(world_size)]
    for p in procs:
        p.start()
    results = {}
    import queue as queue_mod
    import time
    deadline = time.monotonic() + timeout
    try:
        while len(results) < world_size:
            try:
                rank, status, payload = queue.get(timeout=2)
            except queue_mod.Empty:
                # fail fast when a worker died without reporting
                # (segfault / OOM-kill / rendezvous abort)
                dead = [(p.pid, i, p.exitcode) for i, p in enumerate(procs)
                        if not p.is_alive() and p.exitcode not in (0, None)
                        and i not in results]
                if dead:
                    raise RuntimeError(
                        f"worker(s) died without reporting: "
                        f"{[(f'rank {i}', f'exit {code}') for _, i, code in dead]}")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"run_distributed: {world_size - len(results)} worker(s) "
                        f"unreported after {timeout}s (alive: "
                        f"{[p.is_alive() for p in procs]})")
                continue
            if status == "error":
                raise RuntimeError(f"rank {rank} failed:\n{payload}")
            results[rank] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    return results
