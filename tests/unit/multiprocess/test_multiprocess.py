"""True multi-process distributed tests (reference tests/unit/common.py
DistributedTest pattern): N OS processes, each owning its own devices,
rendezvoused through jax.distributed — the real multi-host boot path."""

import os

import numpy as np
import pytest

from unit.multiprocess.dist_harness import run_distributed

pytestmark = pytest.mark.skipif(os.environ.get("DS_SKIP_MULTIPROC") == "1",
                                reason="multi-process tests disabled")


def _psum_worker(rank, world):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.parallel import groups

    assert jax.process_count() == world
    assert len(jax.devices()) == world * 4  # global device view
    mesh = groups.initialize_mesh({"data_parallel_size": world * 4})
    # global array: each process contributes its addressable shards
    sharding = NamedSharding(mesh, P("data"))
    x = jax.make_array_from_callback(
        (world * 4,), sharding, lambda idx: np.asarray([float(idx[0].start)]))
    total = jax.jit(lambda x: jnp.sum(x))(x)
    return float(total)


def test_cross_process_reduction():
    """A global-mesh reduction spanning two processes' devices."""
    out = run_distributed(_psum_worker, world_size=2, devices_per_proc=4)
    assert out[0] == out[1] == float(sum(range(8)))


def _train_worker(rank, world):
    import jax
    import numpy as np
    import deepspeed_tpu
    from unit.simple_model import SimpleModel, random_dataloader

    cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 2},
           "mesh": {"data_parallel_size": world * 4}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16, nlayers=2),
                                               config=cfg)
    x, y = random_dataloader(None, 8, 16, batch_size=8)[0]
    losses = []
    for _ in range(3):
        loss = engine(x, y)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def test_two_process_training_identical_losses():
    """ZeRO-2 training on a mesh spanning two processes: both ranks
    compute the same global loss (single-controller SPMD semantics)."""
    out = run_distributed(_train_worker, world_size=2, devices_per_proc=4, timeout=600)
    assert np.allclose(out[0], out[1], rtol=1e-6), out
    assert np.isfinite(out[0]).all()


def _ckpt_worker(rank, world):
    import jax
    import numpy as np
    import deepspeed_tpu
    from unit.simple_model import SimpleModel, random_dataloader

    ckpt_dir = os.environ["DS_TEST_CKPT_DIR"]
    cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
           "mesh": {"data_parallel_size": world * 4}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16, nlayers=2),
                                               config=cfg)
    x, y = random_dataloader(None, 8, 16, batch_size=8)[0]
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    engine.save_checkpoint(ckpt_dir, tag="mp")  # each process writes ITS shards
    k = engine.params["linear_0"]["kernel"]
    return {"loss": float(loss), "local_shards": len(k.addressable_shards)}


def test_multiprocess_sharded_checkpoint(tmp_path):
    """The sharded engine's collective save across two real processes:
    each writes only its addressable chunks; the merged store holds the
    full state and loads back in one process."""
    out = run_distributed(_ckpt_worker, world_size=2, devices_per_proc=4, timeout=600,
                          extra_env={"DS_TEST_CKPT_DIR": str(tmp_path)})
    assert out[0]["loss"] == out[1]["loss"]
    # both processes contributed chunk files
    sdir = tmp_path / "mp" / "mp_rank_00_model_states.pt.shards"
    files = os.listdir(sdir)
    assert "chunks_p0.json" in files and "chunks_p1.json" in files, files
    assert "data_p0.bin" in files and "data_p1.bin" in files

    # single-process reload of the 2-process checkpoint
    import deepspeed_tpu
    from deepspeed_tpu.parallel import groups
    from unit.simple_model import SimpleModel, random_dataloader
    groups.destroy_mesh()
    cfg = {"train_batch_size": 8, "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 3},
           "mesh": {"data_parallel_size": 8}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=SimpleModel(hidden_dim=16, nlayers=2),
                                               config=cfg)
    x, y = random_dataloader(None, 8, 16, batch_size=8)[0]
    engine(x, y)
    path, _ = engine.load_checkpoint(str(tmp_path), tag="mp")
    assert path is not None
    loss = float(engine(x, y))
    assert np.isfinite(loss)
