"""FleetSupervisor: real replica processes under real signals.

The cross-process acceptance for the wire fleet: ``bin/ds_replica``
workers spawned by :class:`FleetSupervisor`, killed with real
``SIGKILL``, hung past the heartbeat watchdog, crash-looped past the
failure budget — and on the traffic side, a :class:`FleetRouter` over
:class:`WireReplica` clients that must fail a mid-stream ``kill -9``
over to the surviving process with a bit-identical replayed stream.

Heavy workers (they import jax in the child) are shared per class;
budget/watchdog/stop tests use tiny argv-compatible stub workers with
no jax import, so they stay fast.
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from deepspeed_tpu.serving.fleet import FleetConfig, FleetRouter
from deepspeed_tpu.serving.fleet.wire import (FleetSupervisor,
                                              ReplicaProcSpec, WireReplica)
from unit.common.fault_injection import kill_process
from unit.inference.serving.test_admission import FakeEngine

pytestmark = pytest.mark.skipif(
    os.environ.get("DS_SKIP_MULTIPROC") == "1",
    reason="multiprocess tests disabled (DS_SKIP_MULTIPROC=1)")

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
CHILD_ENV = {"PYTHONPATH": f"{REPO}:{os.path.join(REPO, 'tests')}",
             "JAX_PLATFORMS": "cpu"}


def factory_spec(name, fn="make_slow_replica"):
    return ReplicaProcSpec(
        name,
        cmd=[sys.executable, os.path.join(REPO, "bin", "ds_replica"),
             "--factory", f"unit.common.wire_workers:{fn}"],
        env=CHILD_ENV)


def wire_client(sup, name, **kw):
    kw.setdefault("timeout_s", 15.0)
    kw.setdefault("probe_timeout_s", 3.0)
    kw.setdefault("connect_timeout_s", 5.0)
    kw.setdefault("backoff_s", 0.05)
    return WireReplica(name, sup.address(name, timeout=30.0), **kw)


def wait_until(cond, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


# ======================================================================
# the real thing: ds_replica workers, FakeEngine gateways inside
# ======================================================================
class TestSupervisedFleet:
    """One two-replica fleet shared by the ordered tests below (child
    startup imports jax — ~10s per process)."""

    @pytest.fixture(scope="class")
    def fleet(self, tmp_path_factory):
        run_dir = tmp_path_factory.mktemp("fleet")
        sup = FleetSupervisor(
            [factory_spec("r0"), factory_spec("r1")],
            run_dir=str(run_dir), max_restarts=3, monitor_interval=0.1,
            watchdog_timeout=0, grace=5.0)
        sup.start()
        clients = {}
        try:
            for name in ("r0", "r1"):
                clients[name] = wire_client(sup, name)
                wait_until(clients[name].probe, 60.0,
                           f"replica {name} to come up")
            yield sup, clients
        finally:
            for cli in clients.values():
                cli.close()
            sup.stop()

    def test_spawn_announce_and_serve(self, fleet):
        # probes only: both gateways must stay pristine (uid counter 0)
        # so the kill -9 replay below is bit-identical on the survivor
        sup, clients = fleet
        for name in ("r0", "r1"):
            assert sup.running(name)
            assert sup.address(name).startswith("unix:")
            assert clients[name].alive() is True
            assert clients[name].load() == 0

    def test_kill9_midstream_fails_over_bit_identical(self, fleet):
        """THE acceptance: SIGKILL a replica process with a stream in
        flight; the router completes the request on the surviving
        process, replayed prefix verified, stream bit-identical to the
        canonical uid-0 FakeEngine stream. Zero lost requests."""
        sup, clients = fleet
        # the router gets its OWN clients: router.shutdown() detaches
        # them (WireReplica.shutdown closes the client side only — the
        # processes stay up for the tests that follow)
        router = FleetRouter(
            [wire_client(sup, "r0"), wire_client(sup, "r1")],
            config=FleetConfig(retry_backoff_s=0.05,
                               heartbeat_interval_s=0.2,
                               stream_token_timeout_s=20.0),
            auto_heartbeat=False)
        try:
            # SlowFakeEngine paces ~50ms/token: 40 tokens ≈ 2s window
            h = router.submit([1, 2, 3], max_new_tokens=40)
            wait_until(lambda: len(h._collected) >= 2, 30.0,
                       "the stream to start")
            victim = h.replica_trail[0]
            kill_process(sup.pid(victim))  # real SIGKILL, mid-stream
            got = h.result(timeout=60)
            assert got == FakeEngine.expected_tokens(0, 3, 40)
            survivor = ({"r0", "r1"} - {victim}).pop()
            assert h.replica_trail == [victim, survivor]
            assert router.snapshot()["counters"]["failovers"] >= 1
        finally:
            router.shutdown()

    def test_killed_replica_relaunches_on_same_address(self, fleet):
        """The supervisor half of recovery: the monitor relaunches the
        SIGKILLed process (rc normalized to 137), the replacement binds
        the SAME unix socket, and the existing WireReplica reconnects
        to it without re-discovery."""
        sup, clients = fleet
        stats = sup.stats()
        killed = [n for n, s in stats.items() if s["restarts"] > 0]
        assert killed, "previous test killed one replica"
        name = killed[0]
        wait_until(lambda: sup.running(name), 60.0,
                   f"{name} to be relaunched")
        cli = clients[name]
        wait_until(cli.probe, 60.0, f"{name} to serve again")
        # fresh gateway in the replacement process: uid counter reset
        h = cli.submit([1, 2, 3], max_new_tokens=4)
        assert h.result(timeout=30) == FakeEngine.expected_tokens(0, 3, 4)
        assert sup.stats()[name]["state"] == "running"


# ======================================================================
# supervision mechanics: stub workers, no jax in the child
# ======================================================================
STUB = textwrap.dedent("""\
    import argparse, json, os, signal, sys, time

    p = argparse.ArgumentParser()
    p.add_argument("--name"); p.add_argument("--bind")
    p.add_argument("--heartbeat-file"); p.add_argument("--announce-file")
    p.add_argument("--beats", type=int, default=-1)
    p.add_argument("--exit-rc", type=int, default=None)
    p.add_argument("--ignore-term", action="store_true")
    args = p.parse_args()

    if args.exit_rc is not None:
        sys.exit(args.exit_rc)  # immediate-crash worker
    if args.ignore_term:
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
    else:
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    if args.announce_file:
        with open(args.announce_file, "w") as fd:
            fd.write(args.bind or "")
    n = 0
    while True:
        if args.beats < 0 or n < args.beats:
            n += 1
            tmp = args.heartbeat_file + ".tmp"
            with open(tmp, "w") as fd:
                json.dump({"beats": n, "time": time.time()}, fd)
            os.replace(tmp, args.heartbeat_file)
        time.sleep(0.1)
""")


@pytest.fixture
def stub(tmp_path):
    path = tmp_path / "stub_worker.py"
    path.write_text(STUB)

    def spec(name, *extra, **kw):
        return ReplicaProcSpec(
            name, cmd=[sys.executable, str(path)] + list(extra), **kw)

    return spec


class TestSupervisionMechanics:

    def test_crash_loop_exhausts_budget_peers_unaffected(self, stub,
                                                         tmp_path):
        sup = FleetSupervisor(
            [stub("crasher", "--exit-rc", "3"), stub("steady")],
            run_dir=str(tmp_path / "run"), max_restarts=2,
            failure_window=300.0, monitor_interval=0.05,
            watchdog_timeout=0, grace=0.5)
        sup.start()
        try:
            wait_until(
                lambda: sup.stats()["crasher"]["state"] == "failed",
                20.0, "the crash loop to exhaust the budget")
            stats = sup.stats()
            # budget: the initial launch + max_restarts relaunches
            assert stats["crasher"]["restarts"] == 2
            assert stats["crasher"]["failures_in_window"] == 3
            assert stats["steady"]["state"] == "running"
            assert sup.running("steady")  # peers keep serving
        finally:
            sup.stop()

    def test_hang_watchdog_escalates_and_relaunches(self, stub, tmp_path):
        # beats 3 times (~0.3s) then stops; SIGTERM is ignored, so the
        # relaunch requires the full SIGTERM -> grace -> SIGKILL path
        sup = FleetSupervisor(
            [stub("wedge", "--beats", "3", "--ignore-term")],
            run_dir=str(tmp_path / "run"), max_restarts=1,
            monitor_interval=0.05, watchdog_timeout=1.0, grace=0.3)
        sup.start()
        try:
            wait_until(lambda: sup.stats()["wedge"]["hangs"] >= 1, 30.0,
                       "the watchdog to fire")
            wait_until(lambda: sup.stats()["wedge"]["restarts"] >= 1,
                       10.0, "the hung replica to be relaunched")
            # the replacement wedges too; with max_restarts=1 the
            # second hang exhausts the budget
            wait_until(
                lambda: sup.stats()["wedge"]["state"] == "failed",
                30.0, "the second hang to exhaust the budget")
            assert sup.stats()["wedge"]["hangs"] == 2
        finally:
            sup.stop()

    def test_sigkill_rc_is_normalized(self, stub, tmp_path):
        sup = FleetSupervisor(
            [stub("victim")], run_dir=str(tmp_path / "run"),
            max_restarts=1, monitor_interval=0.05, watchdog_timeout=0,
            grace=0.5)
        sup.start()
        try:
            wait_until(lambda: sup.running("victim"), 10.0, "launch")
            pid = sup.pid("victim")
            sup.kill("victim")  # SIGKILL via the supervisor's own hook
            wait_until(
                lambda: sup.running("victim") and sup.pid("victim") != pid,
                20.0, "the relaunch")
            assert sup.stats()["victim"]["restarts"] == 1
        finally:
            sup.stop()

    def test_stop_is_graceful_for_cooperative_workers(self, stub,
                                                      tmp_path):
        sup = FleetSupervisor(
            [stub("a"), stub("b")], run_dir=str(tmp_path / "run"),
            monitor_interval=0.05, watchdog_timeout=0, grace=5.0)
        sup.start()
        try:
            # the announce file is written AFTER the SIGTERM handler is
            # installed — a poll()-based wait would race worker startup
            wait_until(
                lambda: all(os.path.exists(sup._children[n].announce_file)
                            for n in ("a", "b")),
                10.0, "both workers ready")
        finally:
            t0 = time.monotonic()
            sup.stop()
        took = time.monotonic() - t0
        assert took < 4.0  # SIGTERM honored: nobody sat out the grace
        for name in ("a", "b"):
            child = sup._children[name]
            assert child.popen.poll() == 0  # clean exits, no SIGKILL
            assert sup.stats()[name]["state"] == "stopped"

    def test_announce_fallback_is_the_assigned_bind(self, stub,
                                                    tmp_path):
        # a worker that never writes the announce file (exit-rc crashes
        # immediately): address() falls back to the deterministic bind
        sup = FleetSupervisor(
            [stub("mute", "--exit-rc", "0")],
            run_dir=str(tmp_path / "run"), max_restarts=0,
            monitor_interval=0.05, watchdog_timeout=0, grace=0.5)
        sup.start()
        try:
            addr = sup.address("mute", timeout=0.3)
            assert addr == f"unix:{tmp_path / 'run' / 'mute.sock'}"
        finally:
            sup.stop()
