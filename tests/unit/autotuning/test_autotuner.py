"""Autotuner tests (analogue of reference tests/unit/autotuning/test_autotuning.py)."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.autotuning import Autotuner, autotune
from deepspeed_tpu.parallel import groups
from unit.simple_model import SimpleModel

HIDDEN = 32


def batch_fn(mbs):
    rng = np.random.RandomState(0)
    x = rng.randn(mbs, HIDDEN).astype(np.float32)
    y = rng.randint(0, HIDDEN, size=(mbs,)).astype(np.int64)
    return (x, y)


BASE = {
    "train_batch_size": 8,
    "train_micro_batch_size_per_gpu": 1,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    "mesh": {"data_parallel_size": 8},
}


def test_autotuner_picks_and_records(tmp_path):
    groups.destroy_mesh()
    tuner = Autotuner(
        model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=2),
        base_config=BASE,
        batch_fn=batch_fn,
        micro_batches=[8, 16],
        zero_stages=[1],
        steps=2,
        results_dir=str(tmp_path),
    )
    best_cfg = tuner.tune()
    assert best_cfg["zero_optimization"]["stage"] == 1
    assert best_cfg["train_micro_batch_size_per_gpu"] in (8, 16)
    # triangulation derives train_batch_size; it must not be pre-pinned
    assert "train_batch_size" not in best_cfg
    assert best_cfg["gradient_accumulation_steps"] == 1
    assert len(tuner.results) >= 1
    assert all(r["value"] is not None or r["error"] for r in tuner.results)

    results = json.load(open(tmp_path / "autotuning_results.json"))
    assert results == tuner.results
    optimal = json.load(open(tmp_path / "ds_config_optimal.json"))
    assert optimal == best_cfg


def test_autotuner_prunes_on_failure():
    groups.destroy_mesh()

    class Exploding(SimpleModel):
        pass

    calls = []

    def bad_batch(mbs):
        calls.append(mbs)
        if mbs > 8:
            raise MemoryError("synthetic OOM")
        return batch_fn(mbs)

    tuner = Autotuner(
        model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=1),
        base_config=BASE,
        batch_fn=bad_batch,
        micro_batches=[8, 16, 32],
        zero_stages=[0],
        steps=1,
    )
    cfg = tuner.tune()
    # 16 failed → 32 never attempted
    assert 32 not in calls
    failed = [r for r in tuner.results if r["error"]]
    assert len(failed) == 1 and failed[0]["micro_batch_size"] == 16
    assert cfg["train_micro_batch_size_per_gpu"] == 8


def test_autotune_convenience():
    groups.destroy_mesh()
    cfg = autotune(lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=1), BASE, batch_fn,
                   micro_batches=[8], zero_stages=[0], steps=1)
    assert cfg["train_micro_batch_size_per_gpu"] == 8


def test_memory_model_estimates_scale_with_stage_and_offload():
    """mem_model.py (reference autotuner.py:663 model-info profiling +
    cost_model.py): params/grads/opt-state bytes follow the ZeRO stage
    partitioning arithmetic; offload zeroes the optimizer term."""
    groups.destroy_mesh()
    tuner = Autotuner(
        model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=2),
        base_config=BASE, batch_fn=batch_fn, world_size=8,
    )
    e0 = tuner.estimate_memory(0, 8)
    e1 = tuner.estimate_memory(1, 8)
    e3 = tuner.estimate_memory(3, 8)
    eoff = tuner.estimate_memory(2, 8, offload=True)
    assert e0["n_params"] > 0
    # stage 1 shards optimizer state 8-way; stage 3 also shards params
    assert e1["optimizer_bytes"] == e0["optimizer_bytes"] // 8
    assert e3["params_bytes"] == e0["params_bytes"] // 8
    assert e3["total_bytes"] < e1["total_bytes"] < e0["total_bytes"]
    assert eoff["optimizer_bytes"] == 0
    # activations grow with micro-batch
    assert tuner.estimate_memory(0, 16)["activation_bytes"] > e0["activation_bytes"]


def test_memory_budget_prunes_without_running():
    """The done-criterion for the memory model: a config the estimator
    rejects is recorded as pruned and the experiment NEVER runs."""
    groups.destroy_mesh()
    ran = []

    tuner = Autotuner(
        model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=2),
        base_config=BASE, batch_fn=batch_fn,
        micro_batches=[8, 16], zero_stages=[0, 3], steps=1,
        memory_budget_bytes=1,  # nothing fits → everything pruned...
    )
    orig = tuner.run_experiment
    tuner.run_experiment = lambda *a, **k: ran.append(a) or orig(*a, **k)
    with pytest.raises(RuntimeError, match="every experiment failed"):
        tuner.tune()
    assert ran == []  # nothing ever executed
    assert all("estimated OOM" in r["error"] for r in tuner.results)
    assert all("pruned without running" in r["error"] for r in tuner.results)

    # a sane budget lets small configs through and prunes none
    groups.destroy_mesh()
    tuner2 = Autotuner(
        model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=2),
        base_config=BASE, batch_fn=batch_fn,
        micro_batches=[8], zero_stages=[1], steps=1,
        memory_budget_bytes=10 << 30,
    )
    cfg = tuner2.tune()
    assert cfg["train_micro_batch_size_per_gpu"] == 8
    assert all(r["value"] is not None for r in tuner2.results)


def test_gas_and_offload_search_dims():
    """The grid extends over gradient-accumulation and offload when
    configured (reference tuning space covers both)."""
    groups.destroy_mesh()
    tuner = Autotuner(
        model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=1),
        base_config=BASE, batch_fn=batch_fn,
        micro_batches=[8], zero_stages=[1], steps=1,
        gas_candidates=[1, 2],
    )
    cfg = tuner.tune()
    combos = {(r["zero_stage"], r["gas"]) for r in tuner.results}
    assert combos == {(1, 1), (1, 2)}
    assert cfg["gradient_accumulation_steps"] in (1, 2)


def test_memory_estimate_scales_with_gas_and_caches_traces():
    """The fused train_batch saves residuals per micro-step, so the
    activation estimate scales with gradient accumulation; traces are
    cached per mbs so the sweep costs arithmetic only."""
    groups.destroy_mesh()
    tuner = Autotuner(
        model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=2),
        base_config=BASE, batch_fn=batch_fn, world_size=8,
    )
    e1 = tuner.estimate_memory(1, 8, gas=1)
    e8 = tuner.estimate_memory(1, 8, gas=8)
    assert e8["activation_bytes"] == 8 * e1["activation_bytes"]
    assert e8["total_bytes"] > e1["total_bytes"]
    assert list(tuner._mem_trace_cache.keys()) == [8]  # one trace per mbs


def test_tuner_strategies_grid_and_random():
    """Strategy parity with the reference tuner/ package: grid runs every
    candidate; random samples num_trials without the hill-climb."""
    groups.destroy_mesh()
    t = Autotuner(model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=1),
                  base_config=BASE, batch_fn=batch_fn,
                  micro_batches=[8, 16], zero_stages=[0, 1], steps=1)
    t.tune(strategy="grid")
    assert len(t.results) == 4  # full product, no early stop

    groups.destroy_mesh()
    t2 = Autotuner(model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=1),
                   base_config=BASE, batch_fn=batch_fn,
                   micro_batches=[8, 16], zero_stages=[0, 1], steps=1)
    t2.tune(strategy="random", num_trials=2, seed=1)
    assert len(t2.results) == 2

    with pytest.raises(ValueError, match="unknown strategy"):
        t2.tune(strategy="nope")


def test_tuner_strategy_model_based():
    """Model-based tuner (reference tuner/model_based_tuner.py +
    cost_model.py): seeds with random evals, fits a least-squares cost
    model, and spends its remaining budget on model-ranked candidates —
    still finding the true best within the budget."""
    groups.destroy_mesh()
    t = Autotuner(model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=1),
                  base_config=BASE, batch_fn=batch_fn,
                  micro_batches=[8, 16, 32], zero_stages=[0, 1], steps=1)
    t.tune(strategy="model_based", num_trials=4, seed=0)
    ran = [r for r in t.results if r.get("error") is None and r["value"] is not None]
    assert len(ran) == 4  # budget respected (6 candidates, 4 run)

    # Deterministic model-quality check (real timings are too noisy to
    # distinguish close configs): synthetic ground truth where throughput
    # grows with mbs and shrinks with stage. With 3 random seeds + budget
    # 5 over 8 candidates, the fitted cost model must spend the remaining
    # budget well enough to find the true best (stage=0, mbs=64) — a
    # broken ranking (e.g. ascending sort) leaves it undiscovered.
    groups.destroy_mesh()
    tm = Autotuner(model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=1),
                   base_config=BASE, batch_fn=batch_fn,
                   micro_batches=[8, 16, 32, 64], zero_stages=[0, 1], steps=1)

    def fake_run(stage, mbs, gas=None, offload=None):
        rec = {"zero_stage": stage, "micro_batch_size": mbs, "gas": gas,
               "offload": offload, "metric": tm.metric, "error": None,
               "value": float(mbs) / (1.0 + 0.5 * stage)}
        tm.results.append(rec)
        return rec

    tm.run_experiment = fake_run
    tm.tune(strategy="model_based", num_trials=5, seed=0)
    assert (tm.best["zero_stage"], tm.best["micro_batch_size"]) == (0, 64)
