"""Autotuner tests (analogue of reference tests/unit/autotuning/test_autotuning.py)."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.autotuning import Autotuner, autotune
from deepspeed_tpu.parallel import groups
from unit.simple_model import SimpleModel

HIDDEN = 32


def batch_fn(mbs):
    rng = np.random.RandomState(0)
    x = rng.randn(mbs, HIDDEN).astype(np.float32)
    y = rng.randint(0, HIDDEN, size=(mbs,)).astype(np.int64)
    return (x, y)


BASE = {
    "train_batch_size": 8,
    "train_micro_batch_size_per_gpu": 1,
    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    "mesh": {"data_parallel_size": 8},
}


def test_autotuner_picks_and_records(tmp_path):
    groups.destroy_mesh()
    tuner = Autotuner(
        model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=2),
        base_config=BASE,
        batch_fn=batch_fn,
        micro_batches=[8, 16],
        zero_stages=[1],
        steps=2,
        results_dir=str(tmp_path),
    )
    best_cfg = tuner.tune()
    assert best_cfg["zero_optimization"]["stage"] == 1
    assert best_cfg["train_micro_batch_size_per_gpu"] in (8, 16)
    # triangulation derives train_batch_size; it must not be pre-pinned
    assert "train_batch_size" not in best_cfg
    assert best_cfg["gradient_accumulation_steps"] == 1
    assert len(tuner.results) >= 1
    assert all(r["value"] is not None or r["error"] for r in tuner.results)

    results = json.load(open(tmp_path / "autotuning_results.json"))
    assert results == tuner.results
    optimal = json.load(open(tmp_path / "ds_config_optimal.json"))
    assert optimal == best_cfg


def test_autotuner_prunes_on_failure():
    groups.destroy_mesh()

    class Exploding(SimpleModel):
        pass

    calls = []

    def bad_batch(mbs):
        calls.append(mbs)
        if mbs > 8:
            raise MemoryError("synthetic OOM")
        return batch_fn(mbs)

    tuner = Autotuner(
        model_fn=lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=1),
        base_config=BASE,
        batch_fn=bad_batch,
        micro_batches=[8, 16, 32],
        zero_stages=[0],
        steps=1,
    )
    cfg = tuner.tune()
    # 16 failed → 32 never attempted
    assert 32 not in calls
    failed = [r for r in tuner.results if r["error"]]
    assert len(failed) == 1 and failed[0]["micro_batch_size"] == 16
    assert cfg["train_micro_batch_size_per_gpu"] == 8


def test_autotune_convenience():
    groups.destroy_mesh()
    cfg = autotune(lambda: SimpleModel(hidden_dim=HIDDEN, nlayers=1), BASE, batch_fn,
                   micro_batches=[8], zero_stages=[0], steps=1)
    assert cfg["train_micro_batch_size_per_gpu"] == 8
