"""Serving autotuner: traces, knob space, offline search, online SLO
controller.

Four layers under test:

- **traces** (stdlib): seeded synthesis is deterministic, jsonl
  round-trips exactly, prefix-heavy mixes carry their share structure;
- **knob schema / space**: the search space is validated against the
  env registry's typed schema (the same artifact behind
  ``ds_lint --list-knobs --format=json``) and static pruning kills
  arithmetically-impossible candidates before anything is built;
- **offline tuner**: successive halving picks the best SLO-satisfying
  candidate, early-stops violators, and its config JSON round-trips
  through ``load_tuned_config`` / ``DS_AUTOTUNE_CONFIG``;
- **record -> replay determinism** on the REAL v2 engine: a trace
  recorded off a live gateway and replayed twice produces bit-identical
  greedy streams and identical admission decisions;
- **online controller**: hysteresis (no single-tick reactions, no
  oscillation on a step change in load), cheapest-knob-first stepping
  bounded by floors and attach-time defaults, and the hard rollback
  guard (sustained breach -> defaults restored, controller frozen).
"""

import json
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.autotuning import (ModelProfile, OnlineSLOController,
                                      ReplayReport, ServingKnobSpace,
                                      ServingTrace, ServingTuner, TraceRecorder,
                                      autotune_enabled, env_overrides,
                                      load_tuned_config, replay_lockstep,
                                      serving_overrides, static_violations,
                                      synthesize_trace)
from deepspeed_tpu.autotuning.trace import TraceRequest
from deepspeed_tpu.inference.v2 import (DSStateManagerConfig, InferenceEngineV2,
                                        RaggedInferenceEngineConfig)
from deepspeed_tpu.models import build_llama
from deepspeed_tpu.serving import (ServingAutotuneConfig, ServingConfig,
                                   ServingGateway)
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.utils import env_registry


# ===================================================================== traces
class TestTraces:

    def test_synthesis_deterministic_per_seed(self):
        for kind in ("steady", "bursty", "prefix_heavy"):
            a = synthesize_trace(kind, 24, seed=7)
            b = synthesize_trace(kind, 24, seed=7)
            assert [r.to_json() for r in a] == [r.to_json() for r in b]
            c = synthesize_trace(kind, 24, seed=8)
            assert [r.to_json() for r in a] != [r.to_json() for r in c]

    def test_arrivals_sorted_and_tokens_in_vocab(self):
        tr = synthesize_trace("bursty", 64, seed=1, vocab_size=100)
        arrivals = [r.arrival_s for r in tr]
        assert arrivals == sorted(arrivals)
        for r in tr:
            assert r.max_new_tokens >= 1 and len(r.prompt) >= 1
            assert all(3 <= t < 100 for t in r.prompt)

    def test_prefix_heavy_share_structure(self):
        tr = synthesize_trace("prefix_heavy", 32, seed=3, prefix_groups=3,
                              prefix_share_len=8)
        assert tr.summary()["prefix_share"] == 1.0
        by_group = {}
        for r in tr:
            by_group.setdefault(r.prefix_group, set()).add(tuple(r.prompt[:8]))
        assert set(by_group) <= {0, 1, 2}
        for prefixes in by_group.values():
            assert len(prefixes) == 1  # one shared prefix per family

    def test_jsonl_roundtrip(self, tmp_path):
        tr = synthesize_trace("steady", 16, seed=5)
        path = str(tmp_path / "t.trace.jsonl")
        tr.save(path)
        back = ServingTrace.load(path)
        assert [r.to_json() for r in back] == [r.to_json() for r in tr]
        assert back.meta == tr.meta
        # header line first, one JSON object per line
        lines = open(path).read().splitlines()
        assert "trace_meta" in json.loads(lines[0])
        assert len(lines) == 17

    def test_adapter_id_roundtrip_and_v1_compat(self, tmp_path):
        """Trace v2: ``adapter_id`` survives the jsonl round trip, is
        only written when set (base-only v2 payloads stay line-identical
        to v1), and a v1 trace without the field loads as None."""
        tr = synthesize_trace("steady", 4, seed=5)
        tr.requests[1].adapter_id = 7
        tr.requests[3].adapter_id = 42
        path = str(tmp_path / "t.trace.jsonl")
        tr.save(path)
        back = ServingTrace.load(path)
        assert [r.adapter_id for r in back] == [None, 7, None, 42]
        assert [r.to_json() for r in back] == [r.to_json() for r in tr]
        # base-only requests never emit the key
        assert "adapter_id" not in tr.requests[0].to_json()
        # a v1 record (no adapter_id, v1 header) loads with None
        with open(path) as fd:
            lines = fd.read().splitlines()
        v1 = str(tmp_path / "v1.trace.jsonl")
        with open(v1, "w") as fd:
            fd.write(json.dumps({"trace_meta": {"version": 1}}) + "\n")
            fd.write(lines[1] + "\n")
        old = ServingTrace.load(v1)
        assert old.requests[0].adapter_id is None

    def test_recorder_captures_adapter_id(self):
        rec = TraceRecorder()
        rec.record([3, 4, 5], 8, 0)
        rec.record([3, 4, 6], 8, 1, adapter_id=9)
        tr = rec.trace()
        assert [r.adapter_id for r in tr] == [None, 9]
        assert [r.priority for r in tr] == [0, 1]

    def test_sample_schema_roundtrip_and_v2_compat(self, tmp_path):
        """Trace v3: per-request ``sample`` (with its resolved seed) and
        ``schema`` survive the jsonl round trip, are only written when
        set (greedy v3 payloads stay line-identical to v2), and a v2
        trace without the fields loads as None/None."""
        schema = {"type": "object",
                  "properties": {"ok": {"type": "boolean"}}}
        tr = synthesize_trace("steady", 4, seed=5)
        tr.requests[1].sample = {"temperature": 0.9, "top_k": 20, "seed": 123}
        tr.requests[3].sample = {"temperature": 1.1, "seed": 7}
        tr.requests[3].schema = schema
        path = str(tmp_path / "t3.trace.jsonl")
        tr.save(path)
        back = ServingTrace.load(path)
        assert [r.sample for r in back] == [None, tr.requests[1].sample,
                                            None, tr.requests[3].sample]
        assert [r.schema for r in back] == [None, None, None, schema]
        assert [r.to_json() for r in back] == [r.to_json() for r in tr]
        # greedy unconstrained requests never emit the keys
        assert "sample" not in tr.requests[0].to_json()
        assert "schema" not in tr.requests[0].to_json()
        # a v2 record (no sample/schema, v2 header) loads with None
        with open(path) as fd:
            lines = fd.read().splitlines()
        v2 = str(tmp_path / "v2.trace.jsonl")
        with open(v2, "w") as fd:
            fd.write(json.dumps({"trace_meta": {"version": 2}}) + "\n")
            fd.write(lines[1] + "\n")
        old = ServingTrace.load(v2)
        assert old.requests[0].sample is None
        assert old.requests[0].schema is None

    def test_recorder_captures_sample_and_schema(self):
        rec = TraceRecorder()
        rec.record([3, 4, 5], 8, 0)
        rec.record([3, 4, 6], 8, 0, sample={"top_k": 4, "seed": 11},
                   schema={"enum": ["a", "b"]})
        tr = rec.trace()
        assert [r.sample for r in tr] == [None, {"top_k": 4, "seed": 11}]
        assert [r.schema for r in tr] == [None, {"enum": ["a", "b"]}]

    def test_future_version_rejected(self, tmp_path):
        path = str(tmp_path / "future.trace.jsonl")
        with open(path, "w") as fd:
            fd.write(json.dumps({"trace_meta": {"version": 99}}) + "\n")
        with pytest.raises(ValueError, match="version 99"):
            ServingTrace.load(path)

    def test_prefix_slices_in_order(self):
        tr = synthesize_trace("steady", 12, seed=0)
        head = tr.prefix(5)
        assert len(head) == 5
        assert [r.uid for r in head] == [r.uid for r in tr][:5]

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown trace kind"):
            synthesize_trace("spiky", 4)

    def test_recorder_offsets_and_groups(self):
        rec = TraceRecorder(prefix_group_len=4)
        rec.record([1, 2, 3, 4, 9], 8, 0)
        rec.record([1, 2, 3, 4, 7], 4, 1)
        rec.record([5, 6], 2, 0)  # too short for a group
        tr = rec.trace()
        assert tr.requests[0].arrival_s == 0.0  # clock starts at first
        assert tr.requests[0].prefix_group == tr.requests[1].prefix_group == 0
        assert tr.requests[2].prefix_group is None
        assert [r.max_new_tokens for r in tr] == [8, 4, 2]


# ============================================================== knob schema
class TestKnobSchema:

    def test_schema_entries_typed(self):
        schema = {k["name"]: k for k in env_registry.knob_schema()}
        assert "DS_AUTOTUNE" in schema and "DS_SPEC_DRAFT_LEN" in schema
        for entry in schema.values():
            assert entry["type"] in ("bool", "int", "str", "optional_bool",
                                     "optional_str")
            assert entry["tuning"] in (None, "offline", "online", "fixed")
            assert entry["doc_row"].startswith("| `DS_")
        draft = schema["DS_SPEC_DRAFT_LEN"]
        assert draft["tuning"] == "online"
        assert draft["range"] == [0, 32]
        # determinism anchors carry the "fixed" tag (machine-readable
        # replay contract) without ever entering the search space
        assert schema["DS_SEED"]["tuning"] == "fixed"

    def test_tunable_knobs_filters_by_tag(self):
        names = {k.name for k in env_registry.tunable_knobs()}
        online = {k.name for k in env_registry.tunable_knobs("online")}
        assert "DS_SPEC_DRAFT_LEN" in online
        assert online <= names
        assert "DS_AUTOTUNE" not in names  # the enable switch is not a dim
        # "fixed" knobs anchor bit-identical replay: never tunable
        assert "DS_SEED" not in names
        with pytest.raises(ValueError, match="fixed"):
            env_registry.tunable_knobs("fixed")

    def test_register_validation(self):
        with pytest.raises(ValueError, match="unknown tuning tag"):
            env_registry.register("DS_TEST_BAD_TAG", "int", 0, "x", "y",
                                  tuning="sometimes")
        with pytest.raises(ValueError, match="min_value 8 > max_value"):
            env_registry.register("DS_TEST_BAD_RANGE", "int", 0, "x", "y",
                                  min_value=8, max_value=4)
        with pytest.raises(ValueError, match="below min_value"):
            env_registry.register("DS_TEST_BAD_DEFAULT", "int", 0, "x", "y",
                                  min_value=2)
        with pytest.raises(ValueError, match="min/max only apply"):
            env_registry.register("DS_TEST_BAD_KIND", "bool", True, "x", "y",
                                  min_value=0)
        # nothing half-registered by the failed attempts
        for name in ("DS_TEST_BAD_TAG", "DS_TEST_BAD_RANGE",
                     "DS_TEST_BAD_DEFAULT", "DS_TEST_BAD_KIND"):
            with pytest.raises(KeyError):
                env_registry.get_knob(name)

    def test_cli_json_matches_registry(self):
        from tools.graft_lint.cli import (format_knobs_json,
                                          format_knobs_markdown)
        doc = json.loads(format_knobs_json())
        assert doc["version"] == 1
        by_name = {k["name"]: k for k in doc["knobs"]}
        assert "DS_AUTOTUNE" in by_name and "DS_AUTOTUNE_CONFIG" in by_name
        # one source of truth: every markdown table row IS a doc_row
        table_rows = [l for l in format_knobs_markdown().splitlines()
                      if l.startswith("| `DS_")]
        assert sorted(table_rows) == sorted(k["doc_row"]
                                            for k in doc["knobs"])


# ================================================================ knob space
class TestKnobSpace:

    def test_enumerate_and_size(self):
        space = ServingKnobSpace({"serving.token_budget": [32, 64],
                                  "DS_SPEC_DRAFT_LEN": [0, 4, 8]})
        assert space.size() == 6
        combos = space.enumerate()
        assert len(combos) == 6
        assert {"DS_SPEC_DRAFT_LEN": 0, "serving.token_budget": 32} in combos

    def test_untagged_knob_rejected(self):
        with pytest.raises(ValueError, match="no tuning tag"):
            ServingKnobSpace({"DS_FLEET_FAILOVER": [True, False]})

    def test_out_of_range_level_rejected(self):
        with pytest.raises(ValueError, match="above registered max"):
            ServingKnobSpace({"DS_SPEC_DRAFT_LEN": [0, 64]})

    def test_unknown_dimension_rejected(self):
        with pytest.raises(ValueError, match="unknown dimension"):
            ServingKnobSpace({"serving.nope": [1]})

    def test_from_registry_include(self):
        space = ServingKnobSpace.from_registry(
            include=["DS_SPEC_DRAFT_LEN"],
            serving_dims={"serving.token_budget": [64, 128]})
        assert set(space.dims) == {"DS_SPEC_DRAFT_LEN",
                                   "serving.token_budget"}
        assert all(0 <= v <= 32 for v in space.dims["DS_SPEC_DRAFT_LEN"])

    def test_static_pruning_arithmetic(self):
        profile = ModelProfile(param_bytes=4 << 30, num_layers=16,
                               num_kv_heads=8, head_dim=128,
                               hbm_bytes=16 << 30, kv_block_size=16,
                               num_kv_blocks=512, max_ctx_tokens=2048,
                               max_tokens=256)
        assert static_violations({"serving.token_budget": 128}, profile) == []
        # budget over the engine step ceiling
        v = static_violations({"serving.token_budget": 512}, profile)
        assert any("exceeds engine max_tokens" in r for r in v)
        # budget under one KV block can live-lock admission
        v = static_violations({"serving.token_budget": 8}, profile)
        assert any("below one" in r for r in v)
        # draft burst must fit the budget
        v = static_violations({"serving.token_budget": 16,
                               "DS_SPEC_DRAFT_LEN": 31}, profile)
        assert any("spec" in r for r in v)
        # HBM: params + KV pool over the chip
        fat = ModelProfile(param_bytes=15 << 30, num_layers=16,
                           num_kv_heads=8, head_dim=128,
                           hbm_bytes=16 << 30, num_kv_blocks=4096)
        v = static_violations({"serving.token_budget": 128}, fat)
        assert any(r.startswith("hbm:") for r in v)
        # block divisibility
        odd = ModelProfile(param_bytes=1 << 30, num_layers=2,
                           num_kv_heads=2, head_dim=64,
                           kv_block_size=16, max_ctx_tokens=100)
        v = static_violations({"serving.token_budget": 64}, odd)
        assert any("not a multiple" in r for r in v)

    def test_override_serialization(self):
        cand = {"DS_SPEC_DRAFT_LEN": 4, "DS_PREFIX_CACHE": True,
                "serving.token_budget": 96, "serving.max_burst": 8}
        assert env_overrides(cand) == {"DS_SPEC_DRAFT_LEN": "4",
                                       "DS_PREFIX_CACHE": "1"}
        assert serving_overrides(cand) == {"token_budget": 96,
                                           "max_burst": 8}


# ============================================================= offline tuner
class _FakeReplayGateway:
    def __init__(self):
        self.drained = False

    def drain(self):
        self.drained = True


def _fake_replay_factory(latency_of):
    """Replay stub: throughput rises with budget, p99 from the model."""

    def fake_replay(gateway, trace):
        budget = gateway.budget
        n = len(trace)
        return ReplayReport(requests=[], admitted_order=[], completed=n,
                            rejected=0, failed=0, gen_tokens=n * budget,
                            wall_s=float(n), gen_tok_s=float(budget),
                            p50_ttft_ms=latency_of(budget) / 2,
                            p99_ttft_ms=latency_of(budget), snapshot={})
    return fake_replay


class TestServingTuner:

    def _build_fn(self, built):
        def build(candidate):
            gw = _FakeReplayGateway()
            gw.budget = candidate["serving.token_budget"]
            built.append(gw)
            return gw
        return build

    def test_halving_picks_best_under_slo(self, tmp_path):
        space = ServingKnobSpace(
            {"serving.token_budget": [16, 32, 64, 128]})
        trace = synthesize_trace("steady", 32, seed=0)
        built = []
        # p99 = 100 + budget: 128 blows a 200ms SLO, 64 is the best legal
        tuner = ServingTuner(space, trace, self._build_fn(built),
                             slo_p99_ttft_ms=200.0, eta=2,
                             min_rung_requests=4,
                             replay_fn=_fake_replay_factory(
                                 lambda b: 100.0 + b))
        res = tuner.search()
        assert res.best == {"serving.token_budget": 64}
        assert res.predicted["gen_tok_s"] == 64.0
        assert res.predicted["p99_ttft_ms"] == 164.0
        assert len(res.predicted["curve"]) >= 1
        assert res.searched == 4 and res.replays == tuner.replays
        # the violator is ranked below every satisfier
        assert res.leaderboard[0].candidate == res.best
        violators = [s for s in res.leaderboard if s.slo_violated]
        assert [s.candidate["serving.token_budget"] for s in violators] \
            == [128]
        # halving early-stops: far fewer replays than grid x full trace
        assert res.replays < 4 * 4
        assert all(g.drained for g in built)  # teardown ran
        # deployable artifact round-trips
        path = str(tmp_path / "tuned.json")
        res.save(path)
        doc = load_tuned_config(path)
        assert doc["knobs"] == res.best
        assert doc["slo_p99_ttft_ms"] == 200.0

    def test_nothing_satisfies_slo(self):
        space = ServingKnobSpace({"serving.token_budget": [32, 64]})
        trace = synthesize_trace("steady", 8, seed=0)
        tuner = ServingTuner(space, trace, self._build_fn([]),
                             slo_p99_ttft_ms=1.0, eta=2,
                             min_rung_requests=4,
                             replay_fn=_fake_replay_factory(
                                 lambda b: 100.0 + b))
        res = tuner.search()
        assert res.best is None and res.predicted == {}
        # least-bad violator first so the report stays informative
        assert res.leaderboard[0].p99_ttft_ms == 132.0
        assert res.replays == 2  # one rung, then everyone early-stopped

    def test_static_pruning_feeds_report(self):
        space = ServingKnobSpace({"serving.token_budget": [8, 64, 512]})
        profile = ModelProfile(param_bytes=1 << 30, num_layers=2,
                               num_kv_heads=2, head_dim=64,
                               kv_block_size=16, max_tokens=256)
        trace = synthesize_trace("steady", 8, seed=0)
        tuner = ServingTuner(space, trace, self._build_fn([]),
                             profile=profile, eta=2, min_rung_requests=8,
                             replay_fn=_fake_replay_factory(lambda b: 50.0))
        res = tuner.search()
        assert res.searched == 1  # 8 (< block) and 512 (> max_tokens) pruned
        assert len(res.pruned) == 2
        assert res.best == {"serving.token_budget": 64}

    def test_load_tuned_config_errors(self, tmp_path):
        with pytest.raises(ValueError, match="unreadable"):
            load_tuned_config(str(tmp_path / "missing.json"))
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        with pytest.raises(ValueError, match="unreadable"):
            load_tuned_config(str(bad))
        noknobs = tmp_path / "noknobs.json"
        noknobs.write_text(json.dumps({"version": 1}))
        with pytest.raises(ValueError, match="no 'knobs'"):
            load_tuned_config(str(noknobs))
        future = tmp_path / "future.json"
        future.write_text(json.dumps({"version": 99, "knobs": {}}))
        with pytest.raises(ValueError, match="version 99"):
            load_tuned_config(str(future))


# ============================================= record -> replay determinism
@pytest.fixture(scope="module")
def model_and_params():
    model = build_llama("debug")
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def make_engine(model_and_params, max_context=64, n_seqs=8):
    model, params = model_and_params
    cfg = RaggedInferenceEngineConfig(
        kv_block_size=8,
        num_kv_blocks=0,
        state_manager=DSStateManagerConfig(max_ragged_batch_size=96,
                                           max_ragged_sequence_count=n_seqs,
                                           max_tracked_sequences=n_seqs,
                                           max_context=max_context))
    return InferenceEngineV2(model=model, config=cfg, params=params,
                             dtype=jnp.float32)


def _replay_gateway(model_and_params):
    return ServingGateway(
        make_engine(model_and_params),
        config=ServingConfig(token_budget=32, max_burst=4,
                             max_queue_depth=16),
        auto_start=False)


class TestRecordReplayDeterminism:

    def test_recorded_trace_replays_bit_identical(self, model_and_params):
        # 1) record: drive a synthetic workload through a live gateway
        # with a recorder attached — the trace captures OFFERED traffic
        workload = synthesize_trace("steady", 10, seed=11, vocab_size=250,
                                    mean_prompt_len=6, mean_new_tokens=4)
        gw_rec = _replay_gateway(model_and_params)
        rec = gw_rec.attach_recorder(TraceRecorder(prefix_group_len=4))
        replay_lockstep(gw_rec, workload)
        assert gw_rec.detach_recorder() is rec
        recorded = rec.trace()
        gw_rec.drain(timeout=30)
        assert len(recorded) == 10
        assert [list(r.prompt) for r in recorded] == \
            [list(r.prompt) for r in workload]

        # 2) replay the RECORDED trace twice on fresh gateways
        reports = []
        for _ in range(2):
            gw = _replay_gateway(model_and_params)
            reports.append(replay_lockstep(gw, recorded))
            gw.drain(timeout=30)
        a, b = reports

        # bit-identical greedy streams
        assert a.streams() == b.streams()
        assert a.completed == b.completed == 10
        assert a.gen_tokens == b.gen_tokens > 0
        assert sum(len(t) for t in a.streams().values()) == a.gen_tokens
        # identical admission decisions and admission ORDER
        assert a.admission_decisions() == b.admission_decisions()
        assert a.admitted_order == b.admitted_order
        assert sorted(a.admitted_order) == list(range(10))

    def test_lockstep_requires_manual_pump(self, model_and_params):
        gw = ServingGateway(make_engine(model_and_params),
                            config=ServingConfig(max_burst=4))
        try:
            with pytest.raises(ValueError, match="manual-pump"):
                replay_lockstep(gw, synthesize_trace("steady", 2, seed=0))
        finally:
            gw.drain(timeout=30)


# ==================================================== gateway integration
class FakeEngine:
    """Deterministic InferenceEngineV2 stand-in (the surface the
    gateway + scheduler touch; same token arithmetic as the admission
    tests so streams compare exactly)."""

    def __init__(self, max_tokens=64, max_seqs=8, block_size=8,
                 max_ctx_tokens=64, free_blocks=16, max_tracked=8):
        self.max_tokens = max_tokens
        self.max_seqs = max_seqs
        self.block_size = block_size
        self.max_ctx_tokens = max_ctx_tokens
        self.free_blocks = free_blocks
        self.state_manager = types.SimpleNamespace(
            max_tracked_sequences=max_tracked)
        self._seen = {}
        self.destroyed = False

    def put(self, uids, chunks, sample=None):
        out = []
        for uid, toks in zip(uids, chunks):
            self._seen[uid] = self._seen.get(uid, 0) + len(toks)
            out.append((uid * 7 + self._seen[uid]) % 97)
        return np.asarray(out, np.int32)

    def query(self, uid):
        if uid not in self._seen:
            return None
        return self._seen[uid], self.block_size

    def flush(self, uid):
        del self._seen[uid]

    def can_burst(self, uids, k):
        return False

    def destroy(self):
        self.destroyed = True


def _run_fake_workload(gw):
    handles = [gw.submit([3 + i, 4, 5], max_new_tokens=3) for i in range(4)]
    for _ in range(64):
        if all(h.done for h in handles):
            break
        gw._pump_once()
    return [h.result(timeout=1) for h in handles]


class TestGatewayIntegration:

    def test_tri_state_enable(self, monkeypatch):
        on = ServingConfig(autotune=ServingAutotuneConfig(enabled=True))
        off = ServingConfig()
        monkeypatch.delenv("DS_AUTOTUNE", raising=False)
        assert autotune_enabled(on) and not autotune_enabled(off)
        monkeypatch.setenv("DS_AUTOTUNE", "0")
        assert not autotune_enabled(on)  # env wins in both directions
        monkeypatch.setenv("DS_AUTOTUNE", "1")
        assert autotune_enabled(off)

    def test_off_path_identical_and_no_controller(self, monkeypatch):
        monkeypatch.setenv("DS_AUTOTUNE", "0")
        gw_off = ServingGateway(
            FakeEngine(),
            config=ServingConfig(
                max_burst=1,
                autotune=ServingAutotuneConfig(enabled=True)),
            auto_start=False)
        assert gw_off.controller is None  # kill switch beats config
        monkeypatch.delenv("DS_AUTOTUNE", raising=False)
        gw_plain = ServingGateway(FakeEngine(),
                                  config=ServingConfig(max_burst=1),
                                  auto_start=False)
        assert gw_plain.controller is None
        # byte-identical pipeline: same streams either way
        assert _run_fake_workload(gw_off) == _run_fake_workload(gw_plain)

    def test_controller_constructed_and_stopped(self, monkeypatch):
        monkeypatch.setenv("DS_AUTOTUNE", "1")
        gw = ServingGateway(FakeEngine(),
                            config=ServingConfig(max_burst=1),
                            auto_start=False)
        assert gw.controller is not None
        assert gw.controller.defaults["token_budget"] == \
            gw.scheduler.budget
        gw.drain(timeout=5)
        assert gw.controller._thread is None

    def test_tuned_config_applied(self, tmp_path, monkeypatch):
        path = str(tmp_path / "tuned.json")
        with open(path, "w") as fd:
            json.dump({"version": 1,
                       "knobs": {"serving.token_budget": 24,
                                 "serving.max_queue_depth": 7,
                                 "DS_SPEC_DRAFT_LEN": 4}}, fd)
        monkeypatch.setenv("DS_AUTOTUNE_CONFIG", path)
        gw = ServingGateway(FakeEngine(), auto_start=False)
        assert gw.config.token_budget == 24
        assert gw.scheduler.budget == 24
        assert gw.queue.max_depth == 7  # DS_* knob left to the env

    def test_tuned_config_rejects_unknown_serving_knob(self, tmp_path,
                                                       monkeypatch):
        path = str(tmp_path / "tuned.json")
        with open(path, "w") as fd:
            json.dump({"version": 1, "knobs": {"serving.role": "decode"}},
                      fd)
        monkeypatch.setenv("DS_AUTOTUNE_CONFIG", path)
        with pytest.raises(ValueError, match="not a gateway-applicable"):
            ServingGateway(FakeEngine(), auto_start=False)

    def test_tuned_config_unreadable_fails_loudly(self, monkeypatch,
                                                  tmp_path):
        monkeypatch.setenv("DS_AUTOTUNE_CONFIG",
                           str(tmp_path / "missing.json"))
        with pytest.raises(ValueError, match="unreadable"):
            ServingGateway(FakeEngine(), auto_start=False)


# ============================================================== controller
class StubSpec:
    def __init__(self, draft_len):
        self.draft_len_cfg = draft_len

    def set_draft_len(self, n):
        assert n >= 1
        self.draft_len_cfg = int(n)


class StubGateway:
    """The exact surface OnlineSLOController touches, with a settable
    p99 so tests drive the control loop tick-by-tick with no clock."""

    def __init__(self, budget=128, depth=32, draft=4, block_size=16):
        self.scheduler = types.SimpleNamespace(budget=budget)
        self.queue = types.SimpleNamespace(max_depth=depth)
        self.gate = types.SimpleNamespace(block_size=block_size)
        self.engine = types.SimpleNamespace(spec=StubSpec(draft))
        self.metrics = ServingMetrics()
        self.p99_ms = 100.0
        self.samples = 16

    def snapshot(self):
        return {"ttft": {"p99_ms": self.p99_ms, "count": self.samples}}

    def knobs(self):
        return (self.scheduler.budget, self.queue.max_depth,
                self.engine.spec.draft_len_cfg)


def make_controller(gw, **over):
    cfg = dict(p99_ttft_slo_ms=500.0, breach_ticks=2, clear_ticks=2,
               cooldown_ticks=1, rollback_ticks=50, interval_s=0.01)
    cfg.update(over)
    return OnlineSLOController(gw, ServingAutotuneConfig(**cfg))


class TestOnlineController:

    def test_single_breached_tick_does_nothing(self):
        gw = StubGateway()
        ctl = make_controller(gw)
        before = gw.knobs()
        gw.p99_ms = 900.0
        assert ctl.tick() == "hold"  # 1 breach < breach_ticks
        gw.p99_ms = 100.0
        ctl.tick()
        assert gw.knobs() == before and ctl.adjustments == 0

    def test_no_samples_holds(self):
        gw = StubGateway()
        gw.samples = 0
        ctl = make_controller(gw)
        gw.p99_ms = 9000.0
        assert ctl.tick() == "hold"
        assert ctl.adjustments == 0

    def test_step_down_cheapest_first_with_cooldown(self):
        gw = StubGateway(budget=128, depth=32, draft=4)
        ctl = make_controller(gw)
        gw.p99_ms = 900.0
        actions = [ctl.tick() for _ in range(6)]
        # hold, down:draft, cooldown, down:draft, cooldown, down:budget
        downs = [a for a in actions if a.startswith("down:")]
        assert downs == ["down:draft_len", "down:draft_len",
                         "down:token_budget"]
        assert "cooldown" in actions  # every adjustment starts a hold
        assert gw.engine.spec.draft_len_cfg == 1  # 4 -> 2 -> 1, floored
        assert gw.scheduler.budget == 96  # 128 * 3/4

    def test_floors_respected(self):
        gw = StubGateway(budget=32, depth=2, draft=1, block_size=16)
        ctl = make_controller(gw, cooldown_ticks=0, min_queue_depth=2)
        gw.p99_ms = 900.0
        for _ in range(30):
            ctl.tick()
        # budget floored at one KV block, depth at min, draft at 1
        assert gw.scheduler.budget >= 16
        assert gw.queue.max_depth == 2
        assert gw.engine.spec.draft_len_cfg == 1

    def test_step_up_never_past_defaults(self):
        gw = StubGateway(budget=128, depth=32, draft=4)
        ctl = make_controller(gw, cooldown_ticks=0)
        gw.p99_ms = 900.0
        for _ in range(6):
            ctl.tick()
        assert gw.knobs() != (128, 32, 4)
        gw.p99_ms = 50.0
        for _ in range(200):
            ctl.tick()
        assert gw.knobs() == (128, 32, 4)  # fully recovered, not beyond
        assert ctl.converged()

    def test_no_oscillation_on_step_load_change(self):
        # closed loop: the SLO is breached exactly while budget > 96 —
        # a step change in capacity the controller must settle under
        gw = StubGateway(budget=128, depth=32, draft=4)
        ctl = make_controller(gw)

        def world():
            gw.p99_ms = 900.0 if gw.scheduler.budget > 96 else 200.0

        actions = []
        for _ in range(700):
            world()
            actions.append(ctl.tick())
        # converged: the tail holds one level with zero adjustments —
        # the geometric backoff spaces recovery probes further and
        # further apart, so the loop settles instead of oscillating
        tail = actions[-80:]
        assert all(not a.startswith(("down:", "up:")) for a in tail), \
            [a for a in tail if a.startswith(("down:", "up:"))]
        assert gw.scheduler.budget <= 96  # held at the satisfying level
        assert ctl.converged()
        assert ctl.rollbacks == 0
        # direction flips are geometrically rare, not merely legal: a
        # plain-hysteresis loop would flip every ~clear_ticks ticks
        # (~100 times in 700); the backoff caps it at a handful
        ups = sum(1 for a in actions if a.startswith("up:token_budget"))
        assert 1 <= ups <= 10
        stats = ctl.stats()
        assert stats["clear_required"] > ctl.clear_ticks

    def test_rollback_on_sustained_breach(self):
        gw = StubGateway(budget=128, depth=32, draft=4)
        ctl = make_controller(gw, rollback_ticks=8)
        gw.p99_ms = 2000.0  # nothing the controller does helps
        actions = [ctl.tick() for _ in range(12)]
        assert "rollback" in actions
        assert gw.knobs() == (128, 32, 4)  # every knob back to default
        assert actions[-1] == "frozen" and ctl.rollbacks == 1
        adjustments = ctl.adjustments
        for _ in range(5):
            assert ctl.tick() == "frozen"
        assert ctl.adjustments == adjustments  # observes, acts no more
        # published for operators
        snap = gw.metrics.snapshot()
        assert snap["external"]["Serve/Autotune"]["frozen"] == 1
        # reset() re-arms
        ctl.reset()
        gw.p99_ms = 100.0
        assert ctl.tick() == "hold"
        assert not ctl.stats()["frozen"]

    def test_rollback_must_back_breach(self):
        with pytest.raises(ValueError, match="rollback_ticks"):
            make_controller(StubGateway(), breach_ticks=4, rollback_ticks=2)
        with pytest.raises(Exception):  # pydantic-level validation too
            ServingAutotuneConfig(breach_ticks=4, rollback_ticks=2)

    def test_no_spec_engine_skips_draft_knob(self):
        gw = StubGateway(budget=128, depth=32, draft=4)
        gw.engine = types.SimpleNamespace()  # no spec state at all
        ctl = make_controller(gw, cooldown_ticks=0)
        assert ctl.defaults["draft_len"] == 0
        gw.p99_ms = 900.0
        actions = [ctl.tick() for _ in range(4)]
        assert "down:token_budget" in actions  # skipped straight past draft
        assert not any("draft" in a for a in actions)

    def test_background_thread_ticks(self):
        import time
        gw = StubGateway()
        ctl = make_controller(gw, interval_s=0.01)
        ctl.start()
        try:
            deadline = time.monotonic() + 5
            while ctl.ticks == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            ctl.stop()
        assert ctl.ticks > 0
        assert ctl._thread is None
