"""Distributed autotuning scheduler (reference scheduler.py ResourceManager):
slot bookkeeping, out-of-process experiment execution, results tree."""

import json
import os

import pytest

from deepspeed_tpu.autotuning import Autotuner, Node, Reservation, ResourceManager
from deepspeed_tpu.autotuning.scheduler import parse_hostfile


class TestSlotBookkeeping:

    def test_node_reserve_restore(self):
        node = Node("worker-0", 4)
        slots = node.reserve_slots(3)
        assert slots == [0, 1, 2] and node.idle_slots == [3]
        assert node.reserve_slots(2) is None  # only 1 free
        node.restore_slots(slots)
        assert sorted(node.idle_slots) == [0, 1, 2, 3]

    def test_reservation_desc_and_restore(self):
        node = Node("h", 2)
        res = Reservation(node, node.reserve_slots(2))
        assert res.desc() == "h:0,1"
        res.restore_slots()
        assert len(node.idle_slots) == 2

    def test_parse_hostfile(self, tmp_path):
        hf = tmp_path / "hostfile"
        hf.write_text("worker-0 slots=4\n# comment\nworker-1 slots=2\nworker-2\n")
        hosts = parse_hostfile(str(hf))
        assert hosts == {"worker-0": 4, "worker-1": 2, "worker-2": 1}


def _write_exp(results_dir, name, stage, mbs, steps=2):
    exp_dir = os.path.join(results_dir, name)
    os.makedirs(exp_dir, exist_ok=True)
    exp = {"name": name,
           "ds_config": {"train_micro_batch_size_per_gpu": mbs,
                         "gradient_accumulation_steps": 1,
                         "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                         "zero_optimization": {"stage": stage}},
           "model": {"family": "simple", "overrides": {"nlayers": 2}},
           "batch": {"hidden_dim": 16},
           "steps": steps}
    with open(os.path.join(exp_dir, "exp.json"), "w") as f:
        json.dump(exp, f)
    return exp_dir


class TestDistributedExperiments:

    def test_subprocess_experiments_and_results_tree(self, tmp_path):
        """>= 2 experiments run as real subprocesses on the localhost
        'node' and write the reference-style results tree."""
        results_dir = str(tmp_path / "exps")
        paths = [_write_exp(results_dir, "z0_mbs4", 0, 4),
                 _write_exp(results_dir, "z1_mbs8", 1, 8),
                 _write_exp(results_dir, "zX_bad", 9, 4)]  # invalid stage → pruned
        rm = ResourceManager({"localhost": 2}, results_dir,
                             env={"DS_FORCE_PLATFORM": "cpu", "XLA_FLAGS": ""}, timeout=300)
        rm.schedule_experiments(paths)
        finished = rm.run()
        assert rm.status() == {"queued": 0, "running": [], "finished": 3}
        assert finished["z0_mbs4"]["value"] > 0
        assert finished["z1_mbs8"]["value"] > 0
        assert finished["zX_bad"]["value"] is None  # failure captured, not raised
        best, val = rm.parse_results()
        assert best in ("z0_mbs4", "z1_mbs8") and val > 0
        # results tree: per-exp result + logs written by the WORKERS
        for name in ("z0_mbs4", "z1_mbs8"):
            d = os.path.join(results_dir, name)
            assert os.path.exists(os.path.join(d, "exp_result.json"))
            assert os.path.exists(os.path.join(d, "stdout.log"))
        with open(os.path.join(results_dir, "zX_bad", "exp_result.json")) as f:
            bad = json.load(f)
        assert bad["error"]

    def test_autotuner_distributed_mode(self, tmp_path):
        """Autotuner.tune_distributed over a hosts dict: grid scheduled
        as subprocesses, best ds_config returned + optimal config file."""
        results_dir = str(tmp_path / "tune")
        tuner = Autotuner(
            model_fn=None, batch_fn=None,
            base_config={"optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
            micro_batches=[4, 8], zero_stages=[1], steps=2,
            results_dir=results_dir,
            model_spec={"family": "simple", "overrides": {"nlayers": 2}},
            batch_spec={"hidden_dim": 16})
        best_cfg = tuner.tune_distributed(hosts={"localhost": 2},
                                          env={"DS_FORCE_PLATFORM": "cpu", "XLA_FLAGS": ""},
                                          timeout=300)
        assert best_cfg["zero_optimization"]["stage"] == 1
        assert best_cfg["train_micro_batch_size_per_gpu"] in (4, 8)
        assert len(tuner.results) == 2
        assert os.path.exists(os.path.join(results_dir, "autotuning_results.json"))
        assert os.path.exists(os.path.join(results_dir, "ds_config_optimal.json"))

    def test_requires_model_spec(self):
        tuner = Autotuner(model_fn=None, batch_fn=None, base_config={})
        with pytest.raises(ValueError, match="model_spec"):
            tuner.tune_distributed(hosts={"localhost": 1})
