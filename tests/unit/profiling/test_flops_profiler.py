"""Flops profiler tests (analogue of reference
tests/unit/profiling/flops_profiler/test_flops_profiler.py)."""

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.profiling.flops_profiler import FlopsProfiler, get_model_profile, profile_fn
from unit.simple_model import SimpleModel, random_dataloader


def test_dense_flops_exact():
    """One Dense layer: flops = 2*B*I*O (matmul) + B*O (bias add)."""
    B, I, O = 4, 16, 8
    m = nn.Dense(O)
    p = m.init(jax.random.PRNGKey(0), jnp.zeros((B, I)))
    flops, macs, by_mod = profile_fn(lambda v, x: m.apply(v, x), p, jnp.zeros((B, I)))
    assert macs == B * I * O
    assert flops == 2 * B * I * O + B * O


def test_scan_multiplies_by_length():
    def fn(x):
        def body(c, _):
            return c @ c, None
        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    x = jnp.zeros((8, 8))
    flops, macs, _ = profile_fn(fn, x)
    assert macs == 5 * 8 * 8 * 8, macs


def test_llama_profile_close_to_analytic():
    from deepspeed_tpu.models import build_llama
    model = build_llama("debug")
    ids = np.zeros((2, 32), np.int32)
    flops, macs, params = get_model_profile(model, args=[ids, ids], as_string=False,
                                            print_profile=False)
    # dense fwd flops ≈ 2 * params * tokens (embedding gather is free)
    analytic = 2 * params * ids.size
    assert 0.6 * analytic < flops < 1.4 * analytic, (flops, analytic)


def test_per_module_attribution():
    from deepspeed_tpu.models import build_llama
    model = build_llama("debug")
    ids = np.zeros((2, 16), np.int32)
    prof = FlopsProfiler(model=model)
    variables = model.init(jax.random.PRNGKey(0), ids, ids)
    prof.profile_model(variables["params"], ids, ids, time_it=False)
    paths = list(prof.by_module)
    assert any("layers" in p for p in paths), paths
    assert any("lm_head" in p for p in paths), paths
    # the transformer body dominates
    body = sum(f for p, (f, m) in prof.by_module.items() if "layers" in p)
    assert body > 0.5 * prof.total_flops


def test_engine_profile_hook(capsys):
    groups.destroy_mesh()
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {"data_parallel_size": 8},
        "flops_profiler": {"enabled": True, "profile_step": 1},
    }
    model = SimpleModel(hidden_dim=32, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    x, y = random_dataloader(None, 8, 32, batch_size=8)[0]
    loss = engine(x, y)
    engine.backward(loss)
    engine.step()
    out = capsys.readouterr().out
    assert "Flops Profiler" in out
    assert "fwd flops" in out
    # printed exactly once
    engine(x, y)
    assert "Flops Profiler" not in capsys.readouterr().out


def test_engine_profile_hook_train_batch(capsys):
    """The fused train_batch path must also trigger the profiler."""
    groups.destroy_mesh()
    config = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {"data_parallel_size": 8},
        "flops_profiler": {"enabled": True, "profile_step": 1},
    }
    model = SimpleModel(hidden_dim=32, nlayers=2)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    x, y = random_dataloader(None, 8, 32, batch_size=8)[0]
    engine.train_batch(batch=(x, y))
    assert "Flops Profiler" in capsys.readouterr().out


def test_formatting_helpers():
    from deepspeed_tpu.profiling.flops_profiler.profiler import (duration_to_string,
                                                                 flops_to_string,
                                                                 params_to_string)
    assert flops_to_string(2.5e12) == "2.50 TFLOPS"
    assert params_to_string(7e9) == "7.00 G"
    assert duration_to_string(0.25) == "250.00 ms"
