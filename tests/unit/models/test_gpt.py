"""GPT-lineage model family tests: the architecture axes that separate
the reference's injection containers (gpt2/gptj/gptneox/opt/bloom,
``deepspeed/module_inject/containers/``) and v2 zoo (falcon/opt/phi,
``deepspeed/inference/v2/model_implementations/``): learned/rotary/ALiBi
positions, sequential vs parallel blocks, MHA/MQA, and TP training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import build_gpt
from deepspeed_tpu.models.gpt import alibi_slopes, init_gpt_cache

DEBUG_PRESETS = ["gpt2-debug", "opt-debug", "bloom-debug", "gptj-debug", "falcon-debug",
                 "neox-debug"]


def _batch(cfg, B=2, S=16, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, cfg.vocab_size, size=(B, S)).astype(np.int32)


class TestGPTForward:

    @pytest.mark.parametrize("preset", DEBUG_PRESETS)
    def test_loss_and_grad_finite(self, preset):
        model = build_gpt(preset)
        ids = _batch(model.config)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        loss, logits = model.apply({"params": params}, ids, ids)
        assert logits.shape == (2, 16, model.config.vocab_size)
        assert np.isfinite(float(loss))
        g = jax.grad(lambda p: model.apply({"params": p}, ids, ids)[0])(params)
        flat = jax.tree_util.tree_leaves(g)
        assert all(np.all(np.isfinite(np.asarray(x))) for x in flat)

    def test_scanned_params_have_layer_dim(self):
        model = build_gpt("gpt2-debug")
        ids = _batch(model.config)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        k = params["model"]["layers"]["attn"]["q_proj"]["kernel"]
        assert k.shape[0] == model.config.num_hidden_layers

    def test_mqa_falcon_kv_heads(self):
        model = build_gpt("falcon-debug")
        ids = _batch(model.config)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        k = params["model"]["layers"]["attn"]["k_proj"]["kernel"]
        assert k.shape[-1] == model.config.head_dim  # 1 kv head

    def test_two_norm_parallel_block_has_both_norms(self):
        model = build_gpt("neox-debug")
        ids = _batch(model.config)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        layers = params["model"]["layers"]
        assert "input_layernorm" in layers and "mlp_layernorm" in layers

    def test_alibi_slopes_pow2_and_non_pow2(self):
        s8 = alibi_slopes(8)
        # standard Bloom slopes for 8 heads: 2^-1 ... 2^-8... actually
        # geometric with ratio 2^(-1): [0.5, 0.25, ...]
        np.testing.assert_allclose(s8, [2 ** (-(i + 1)) for i in range(8)], rtol=1e-6)
        s6 = alibi_slopes(6)
        assert s6.shape == (6,) and np.all(s6 > 0) and np.all(np.diff(s6[:4]) < 0)

    def test_learned_positions_shift_matters(self):
        """Same tokens at different start positions give different logits
        (learned positions are live)."""
        model = build_gpt("gpt2-debug", remat=False)
        ids = _batch(model.config, S=8)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        cache = init_gpt_cache(model.config, 2, 32, dtype=jnp.float32)
        l0, _ = model.apply({"params": params}, ids, cache=cache, start_pos=0)
        l4, _ = model.apply({"params": params}, ids, cache=cache, start_pos=4)
        assert float(jnp.abs(l0 - l4).max()) > 1e-3


class TestGPTDecode:

    @pytest.mark.parametrize("preset", DEBUG_PRESETS)
    def test_prefill_decode_equals_full_forward(self, preset):
        model = build_gpt(preset, remat=False)
        ids = _batch(model.config, S=16)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        cache = init_gpt_cache(model.config, 2, 32, dtype=jnp.float32)
        lp, cache = model.apply({"params": params}, ids[:, :8], cache=cache, start_pos=0)
        full8 = model.apply({"params": params}, ids[:, :8])
        np.testing.assert_allclose(np.asarray(lp), np.asarray(full8), atol=1e-4, rtol=1e-4)
        ld, cache = model.apply({"params": params}, ids[:, 8:9], cache=cache, start_pos=8)
        full9 = model.apply({"params": params}, ids[:, :9])
        np.testing.assert_allclose(np.asarray(ld[:, 0]), np.asarray(full9[:, 8]),
                                   atol=1e-4, rtol=1e-4)


class TestGPTSharded:

    def test_tp_engine_train(self):
        model = build_gpt("gpt2-debug")
        config = {
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"tensor_parallel_size": 2, "sequence_parallel_size": 2},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        ids = _batch(model.config, B=4, S=16)
        losses = [float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
                  for _ in range(3)]
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
        # column-parallel q_proj genuinely sharded over 'tensor'
        k = engine.params["model"]["layers"]["attn"]["q_proj"]["kernel"]
        assert not k.sharding.is_fully_replicated

    def test_zero3_alibi_train(self):
        """Bloom-style ALiBi model under ZeRO-3 (bias path + param sharding)."""
        model = build_gpt("bloom-debug")
        config = {
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        ids = _batch(model.config, B=8, S=16)
        loss = engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))
        assert np.isfinite(float(loss))
