"""Flagship model tests: forward, loss, TP/SP sharding, engine training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import LLAMA_CONFIGS, build_llama, causal_lm_loss
from deepspeed_tpu.parallel import groups


def _batch(cfg, B=2, S=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, size=(B, S)).astype(np.int32)
    return ids


class TestLlamaForward:

    def test_logits_shape_and_loss(self):
        model = build_llama("debug")
        cfg = model.config
        ids = _batch(cfg)
        variables = model.init(jax.random.PRNGKey(0), ids)
        logits = model.apply(variables, ids)
        assert logits.shape == (2, 16, cfg.vocab_size)
        loss, logits2 = model.apply(variables, ids, ids)
        assert np.isfinite(float(loss))
        np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2), rtol=1e-5)

    def test_scanned_params_have_layer_dim(self):
        model = build_llama("debug")
        ids = _batch(model.config)
        variables = model.init(jax.random.PRNGKey(0), ids)
        k = variables["params"]["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert k.shape[0] == model.config.num_hidden_layers

    def test_loss_ignore_index(self):
        logits = jnp.zeros((1, 4, 8))
        labels = jnp.array([[1, 2, -100, 3]])
        loss = causal_lm_loss(logits, labels)
        # uniform logits -> loss == log(8) over the 2 unmasked targets
        np.testing.assert_allclose(float(loss), np.log(8), rtol=1e-5)

    def test_gqa_kv_heads(self):
        model = build_llama("debug", num_key_value_heads=2, num_attention_heads=4)
        ids = _batch(model.config)
        variables = model.init(jax.random.PRNGKey(0), ids)
        k = variables["params"]["model"]["layers"]["self_attn"]["k_proj"]["kernel"]
        assert k.shape[-1] == 2 * model.config.head_dim


class TestLlamaSharded:

    def test_tp_sp_engine_train(self):
        """Train on a tp=2, sp=2, dp=2 mesh end-to-end through the engine."""
        model = build_llama("debug")
        config = {
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"tensor_parallel_size": 2, "sequence_parallel_size": 2},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        ids = _batch(model.config, B=4, S=16)
        losses = []
        for step in range(3):
            loss = engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))
            losses.append(float(loss))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0], f"loss did not decrease: {losses}"

    def test_zero3_param_sharding(self):
        model = build_llama("debug")
        config = {
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        ids = _batch(model.config, B=8, S=16)
        loss = engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))
        assert np.isfinite(float(loss))
        # q_proj kernel must actually be sharded over the zero axes
        k = engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert not k.sharding.is_fully_replicated


class TestLlamaMoE:

    def test_moe_forward_and_train(self):
        model = build_llama("debug", moe_num_experts=4, moe_top_k=2)
        config = {
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "mesh": {"expert_parallel_size": 4},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        ids = _batch(model.config, B=8, S=16)
        loss = engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))
        assert np.isfinite(float(loss))
        w1 = engine.params["model"]["layers"]["moe_mlp"]["deepspeed_moe"]["experts_w1"]
        assert w1.shape[1] == 4  # (L, E, D, I)
        # expert dim (axis 1) genuinely sharded over the 4-way expert axis
        assert w1.sharding.shard_shape(w1.shape)[1] == 1
