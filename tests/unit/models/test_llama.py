"""Flagship model tests: forward, loss, TP/SP sharding, engine training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import LLAMA_CONFIGS, build_llama, causal_lm_loss
from deepspeed_tpu.parallel import groups


def _batch(cfg, B=2, S=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, size=(B, S)).astype(np.int32)
    return ids


class TestLlamaForward:

    def test_logits_shape_and_loss(self):
        model = build_llama("debug")
        cfg = model.config
        ids = _batch(cfg)
        variables = model.init(jax.random.PRNGKey(0), ids)
        logits = model.apply(variables, ids)
        assert logits.shape == (2, 16, cfg.vocab_size)
        loss, logits2 = model.apply(variables, ids, ids)
        assert np.isfinite(float(loss))
        np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2), rtol=1e-5)

    def test_scanned_params_have_layer_dim(self):
        model = build_llama("debug")
        ids = _batch(model.config)
        variables = model.init(jax.random.PRNGKey(0), ids)
        k = variables["params"]["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert k.shape[0] == model.config.num_hidden_layers

    def test_loss_ignore_index(self):
        logits = jnp.zeros((1, 4, 8))
        labels = jnp.array([[1, 2, -100, 3]])
        loss = causal_lm_loss(logits, labels)
        # uniform logits -> loss == log(8) over the 2 unmasked targets
        np.testing.assert_allclose(float(loss), np.log(8), rtol=1e-5)

    def test_gqa_kv_heads(self):
        model = build_llama("debug", num_key_value_heads=2, num_attention_heads=4)
        ids = _batch(model.config)
        variables = model.init(jax.random.PRNGKey(0), ids)
        k = variables["params"]["model"]["layers"]["self_attn"]["k_proj"]["kernel"]
        assert k.shape[-1] == 2 * model.config.head_dim


class TestLlamaSharded:

    def test_tp_sp_engine_train(self):
        """Train on a tp=2, sp=2, dp=2 mesh end-to-end through the engine."""
        model = build_llama("debug")
        config = {
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"tensor_parallel_size": 2, "sequence_parallel_size": 2},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        ids = _batch(model.config, B=4, S=16)
        losses = []
        for step in range(3):
            loss = engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))
            losses.append(float(loss))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0], f"loss did not decrease: {losses}"

    def test_zero3_param_sharding(self):
        model = build_llama("debug")
        config = {
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        ids = _batch(model.config, B=8, S=16)
        loss = engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))
        assert np.isfinite(float(loss))
        # q_proj kernel must actually be sharded over the zero axes
        k = engine.params["model"]["layers"]["self_attn"]["q_proj"]["kernel"]
        assert not k.sharding.is_fully_replicated


class TestLlamaMoE:

    def test_moe_forward_and_train(self):
        model = build_llama("debug", moe_num_experts=4, moe_top_k=2)
        config = {
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "mesh": {"expert_parallel_size": 4},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        ids = _batch(model.config, B=8, S=16)
        loss = engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids)))
        assert np.isfinite(float(loss))
        w1 = engine.params["model"]["layers"]["moe_mlp"]["deepspeed_moe"]["experts_w1"]
        assert w1.shape[1] == 4  # (L, E, D, I)
        # expert dim (axis 1) genuinely sharded over the 4-way expert axis
        assert w1.sharding.shard_shape(w1.shape)[1] == 1


class TestChunkedLoss:
    """Long-sequence chunked cross-entropy (models/llama.py loss_chunk):
    the [S, vocab] logits never materialize — loss and grads must match
    the full-logits path exactly, including the -100 ignore mask and the
    tied-embedding head."""

    def _parity(self, **kw):
        from deepspeed_tpu.models import build_llama
        model_c = build_llama("debug", loss_chunk=16, **kw)
        model_f = build_llama("debug", loss_chunk=0, **kw)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, 256, size=(2, 128)).astype(np.int32))
        labels = np.asarray(ids).copy()
        labels[0, :7] = -100
        labels = jnp.asarray(labels)
        params = model_f.init(jax.random.PRNGKey(0), ids)["params"]

        def loss_of(m):
            return lambda p: m.apply({"params": p}, ids, labels)[0]

        lf, gf = jax.value_and_grad(loss_of(model_f))(params)
        lc, gc_ = jax.value_and_grad(loss_of(model_c))(params)
        np.testing.assert_allclose(float(lf), float(lc), rtol=1e-6)
        for (ka, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(gf),
                                   jax.tree_util.tree_leaves_with_path(gc_)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-6, err_msg=str(ka))

    def test_untied_head_parity(self):
        self._parity()

    def test_tied_embeddings_parity(self):
        self._parity(tie_word_embeddings=True)

    def test_short_seq_keeps_logits(self):
        from deepspeed_tpu.models import build_llama
        model = build_llama("debug")  # S=64 < 2*loss_chunk → full path
        ids = jnp.asarray(np.arange(2 * 64, dtype=np.int32).reshape(2, 64) % 256)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        loss, logits = model.apply({"params": params}, ids, ids)
        assert logits is not None and logits.shape == (2, 64, 256)
