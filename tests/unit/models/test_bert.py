"""BERT-family encoder tests (reference containers bert/distil_bert +
the fused encoder kernel path, csrc/transformer): masking semantics,
heads, and engine training under TP."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import build_bert


def _ids(cfg, B=2, S=16, seed=0):
    return np.random.RandomState(seed).randint(0, cfg.vocab_size, size=(B, S)).astype(np.int32)


class TestBertForward:

    @pytest.mark.parametrize("preset", ["bert-debug", "distilbert-debug"])
    def test_mlm_loss_and_grads(self, preset):
        model = build_bert(preset)
        ids = _ids(model.config)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        labels = np.where(np.arange(16) % 4 == 0, ids, -100).astype(np.int32)
        loss, logits = model.apply({"params": params}, ids, labels)
        assert logits.shape == (2, 16, model.config.vocab_size)
        assert np.isfinite(float(loss))
        g = jax.grad(lambda p: model.apply({"params": p}, ids, labels)[0])(params)
        assert all(np.all(np.isfinite(np.asarray(x))) for x in jax.tree.leaves(g))

    def test_padding_mask_isolates_pad_content(self):
        """Changing the CONTENT of padded positions must not change the
        valid positions' outputs when attention_mask excludes them."""
        model = build_bert("bert-debug")
        ids = _ids(model.config, S=12)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        mask = np.ones((2, 12), np.int32)
        mask[:, 8:] = 0
        ids2 = ids.copy()
        ids2[:, 8:] = (ids2[:, 8:] + 7) % model.config.vocab_size
        out1 = model.apply({"params": params}, ids, attention_mask=jnp.asarray(mask))
        out2 = model.apply({"params": params}, ids2, attention_mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(out1[:, :8]), np.asarray(out2[:, :8]),
                                   rtol=1e-5, atol=1e-5)
        # and WITHOUT the mask they must differ (bidirectional attention)
        out3 = model.apply({"params": params}, ids)
        out4 = model.apply({"params": params}, ids2)
        assert float(jnp.abs(out3[:, :8] - out4[:, :8]).max()) > 1e-4

    def test_token_types_shift_output(self):
        model = build_bert("bert-debug")
        ids = _ids(model.config)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        tt = np.zeros((2, 16), np.int32)
        tt[:, 8:] = 1
        out0 = model.apply({"params": params}, ids)
        out1 = model.apply({"params": params}, ids, token_type_ids=jnp.asarray(tt))
        assert float(jnp.abs(out0 - out1).max()) > 1e-4

    def test_classification_head(self):
        model = build_bert("bert-debug", head="classification", num_labels=3)
        ids = _ids(model.config)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        labels = jnp.asarray([0, 2])
        loss, logits = model.apply({"params": params}, ids, labels)
        assert logits.shape == (2, 3) and np.isfinite(float(loss))


class TestBertSharded:

    def test_tp_engine_mlm_train(self):
        model = build_bert("bert-debug")
        config = {
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "mesh": {"tensor_parallel_size": 2},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        ids = _ids(model.config, B=4)
        losses = [float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
                  for _ in range(3)]
        assert all(np.isfinite(l) for l in losses) and losses[-1] < losses[0]
        k = engine.params["model"]["layers"]["q_proj"]["kernel"]
        assert not k.sharding.is_fully_replicated
