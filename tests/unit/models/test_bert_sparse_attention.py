"""Sparse attention wired into the BERT family via ds_config (reference
sparse_attention_utils.py:81 replace_model_self_attention_with_
sparse_self_attention — BERT/RoBERTa module surgery; on TPU the swap is
a config decision the encoder blocks read)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.bert import BERT_CONFIGS, BertForMaskedLM
from deepspeed_tpu.ops.sparse_attention import SparseAttentionUtils
from deepspeed_tpu.ops.sparse_attention.sparsity_config import FixedSparsityConfig


def _data(S=64, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, 250, size=(2, S)).astype(np.int32)
    mask = np.ones((2, S), np.int32)
    mask[1, S - 10:] = 0  # padded tail on row 1
    return jnp.asarray(ids), jnp.asarray(mask)


def test_dense_mode_matches_plain_attention():
    """mode='dense' admits every block: logits equal the einsum path."""
    model = BertForMaskedLM(BERT_CONFIGS["bert-debug"])
    ids, mask = _data()
    params = model.init(jax.random.PRNGKey(0), ids, attention_mask=mask)["params"]
    want = model.apply({"params": params}, ids, attention_mask=mask)

    sparse = SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention(
        model, ds_config={"sparse_attention": {"mode": "dense", "block": 16}})
    assert sparse.config.sparse_attention is not None
    got = sparse.apply({"params": params}, ids, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_bigbird_mode_runs_and_trains():
    model = BertForMaskedLM(BERT_CONFIGS["bert-debug"])
    ids, mask = _data()
    sparse = SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention(
        model, ds_config={"sparse_attention": {
            "mode": "bigbird", "block": 16, "num_random_blocks": 1,
            "num_sliding_window_blocks": 1, "num_global_blocks": 1}})
    params = sparse.init(jax.random.PRNGKey(0), ids, attention_mask=mask)["params"]
    dense_logits = model.apply({"params": params}, ids, attention_mask=mask)
    sparse_logits = sparse.apply({"params": params}, ids, attention_mask=mask)
    assert not np.allclose(np.asarray(sparse_logits), np.asarray(dense_logits))
    labels = jnp.where(ids % 5 == 0, ids, -100)

    def loss_fn(p):
        return sparse.apply({"params": p}, ids, attention_mask=mask, labels=labels)[0]

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    for leaf in jax.tree.leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_sparsity_config_instance_and_family_guard():
    model = BertForMaskedLM(BERT_CONFIGS["bert-debug"])
    sparse = SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention(
        model, sparsity_config=FixedSparsityConfig(num_heads=4, block=16,
                                                   num_local_blocks=2))
    section = dict(sparse.config.sparse_attention)
    assert section["mode"] == "fixed" and section["num_local_blocks"] == 2
    ids, mask = _data()
    out = sparse.apply({"params": sparse.init(jax.random.PRNGKey(1), ids,
                                              attention_mask=mask)["params"]},
                       ids, attention_mask=mask)
    assert np.all(np.isfinite(np.asarray(out)))

    from deepspeed_tpu.models import build_llama
    with pytest.raises(NotImplementedError, match="BERT family"):
        SparseAttentionUtils.replace_model_self_attention_with_sparse_self_attention(
            build_llama("debug"), ds_config={"sparse_attention": {"mode": "dense"}})
