"""HF checkpoint import: logits parity against transformers itself.

The reference's injection path wraps HF torch models in place
(``module_inject/replace_module.py``); here the weights convert into the
native flax layout, and these tests assert the converted model produces
the SAME logits as the original HF torch model — the strongest possible
interop check."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from deepspeed_tpu.module_inject import from_hf  # noqa: E402

TOL = dict(rtol=2e-4, atol=2e-4)


def _hf_logits(hf_model, ids):
    with torch.no_grad():
        return hf_model(torch.from_numpy(ids).long()).logits.float().numpy()


def _ours_logits(model, params, ids, **kw):
    p32 = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params)
    import dataclasses
    m = model.clone(config=dataclasses.replace(model.config, remat=False))
    out = m.apply({"params": p32}, jnp.asarray(ids), **kw)
    return np.asarray(out, np.float32)


def _check(hf_model, ids, **kw):
    hf_model.eval()
    model, params = from_hf(hf_model)
    np.testing.assert_allclose(_ours_logits(model, params, ids, **kw),
                               _hf_logits(hf_model, ids), **TOL)


IDS = np.arange(2 * 12).reshape(2, 12).astype(np.int32) % 120


class TestHFImportParity:

    def test_llama_gqa(self):
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64)
        _check(transformers.LlamaForCausalLM(cfg), IDS)

    def test_qwen2_attention_bias(self):
        cfg = transformers.Qwen2Config(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64)
        _check(transformers.Qwen2ForCausalLM(cfg), IDS)

    def test_llama_attention_bias_all_projections(self):
        """HF LlamaAttention with attention_bias=True biases o_proj too;
        the import must carry all four biases (exact logit parity)."""
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            attention_bias=True)
        _check(transformers.LlamaForCausalLM(cfg), IDS)

    def test_gemma_geglu_scaled_embed(self):
        """Gemma: (1+w) RMSNorm folded into the native scale, GeGLU,
        sqrt(hidden) embedding scaling, explicit head_dim decoupled from
        hidden/heads, tied head — exact logit parity."""
        cfg = transformers.GemmaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            max_position_embeddings=64, hidden_activation="gelu_pytorch_tanh")
        _check(transformers.GemmaForCausalLM(cfg), IDS)

    def test_mistral_nemo_decoupled_head_dim(self):
        """Mistral-Nemo layout: head_dim explicit and != hidden/heads."""
        cfg = transformers.MistralConfig(
            vocab_size=128, hidden_size=40, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, head_dim=16,
            max_position_embeddings=64, sliding_window=None)
        _check(transformers.MistralForCausalLM(cfg), IDS)

    def test_mixtral_moe(self):
        cfg = transformers.MixtralConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            num_local_experts=4, num_experts_per_tok=2)
        hf = transformers.MixtralForCausalLM(cfg)
        hf.eval()
        model, params = from_hf(hf)
        # dense path needs ample capacity to be dropless like HF routing
        import dataclasses
        model = model.clone(config=dataclasses.replace(model.config,
                                                       moe_capacity_factor=64.0))
        np.testing.assert_allclose(_ours_logits(model, params, IDS),
                                   _hf_logits(hf, IDS), **TOL)

    def test_gpt2(self):
        cfg = transformers.GPT2Config(
            vocab_size=128, n_embd=32, n_inner=64, n_layer=2, n_head=4, n_positions=64)
        _check(transformers.GPT2LMHeadModel(cfg), IDS)

    def test_opt(self):
        cfg = transformers.OPTConfig(
            vocab_size=128, hidden_size=32, ffn_dim=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64, word_embed_proj_dim=32)
        _check(transformers.OPTForCausalLM(cfg), IDS)

    def test_bloom_alibi(self):
        cfg = transformers.BloomConfig(
            vocab_size=128, hidden_size=32, n_layer=2, n_head=4)
        _check(transformers.BloomForCausalLM(cfg), IDS)

    def test_gptj_interleaved_rotary(self):
        cfg = transformers.GPTJConfig(
            vocab_size=128, n_embd=32, n_inner=64, n_layer=2, n_head=4, n_positions=64,
            rotary_dim=4)
        _check(transformers.GPTJForCausalLM(cfg), IDS)

    def test_gpt_neox_parallel_two_norms(self):
        cfg = transformers.GPTNeoXConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64, rotary_pct=0.25)
        _check(transformers.GPTNeoXForCausalLM(cfg), IDS)

    def test_falcon_mqa(self):
        cfg = transformers.FalconConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
            new_decoder_architecture=False, multi_query=True, parallel_attn=True,
            bias=False, max_position_embeddings=64)
        _check(transformers.FalconForCausalLM(cfg), IDS)

    def test_falcon_40b_style_new_arch_gqa(self):
        cfg = transformers.FalconConfig(
            vocab_size=128, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
            num_kv_heads=2, new_decoder_architecture=True, bias=False,
            max_position_embeddings=64)
        _check(transformers.FalconForCausalLM(cfg), IDS)

    def test_phi_partial_rotary(self):
        cfg = transformers.PhiConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64, partial_rotary_factor=0.5)
        _check(transformers.PhiForCausalLM(cfg), IDS)

    def test_bert_mlm(self):
        cfg = transformers.BertConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=64, type_vocab_size=2)
        hf = transformers.BertForMaskedLM(cfg)
        _check(hf, IDS)

    def test_llama3_rope_scaling(self):
        """Llama-3.x wavelength-dependent inv_freq rescale converts with
        exact parity."""
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            rope_scaling={"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
                          "high_freq_factor": 4.0, "original_max_position_embeddings": 32})
        _check(transformers.LlamaForCausalLM(cfg), IDS)

    def test_linear_rope_scaling(self):
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            rope_scaling={"rope_type": "linear", "factor": 2.0})
        _check(transformers.LlamaForCausalLM(cfg), IDS)

    def test_unsupported_variants_raise_clearly(self):
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=1,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            rope_scaling={"rope_type": "yarn", "factor": 2.0,
                          "original_max_position_embeddings": 32})
        with pytest.raises(NotImplementedError, match="rope_scaling"):
            from_hf(transformers.LlamaForCausalLM(cfg))
        cfg = transformers.MistralConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=1,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=8192,
            sliding_window=16)
        with pytest.raises(NotImplementedError, match="sliding_window"):
            from_hf(transformers.MistralForCausalLM(cfg))
        # ...and the escape hatch works
        model, params = from_hf(transformers.MistralForCausalLM(cfg),
                                ignore_sliding_window=True)
        assert model.config.num_hidden_layers == 1
        cfg = transformers.OPTConfig(
            vocab_size=128, hidden_size=32, ffn_dim=64, num_hidden_layers=1,
            num_attention_heads=4, max_position_embeddings=64, word_embed_proj_dim=16)
        with pytest.raises(NotImplementedError, match="word_embed_proj_dim"):
            from_hf(transformers.OPTForCausalLM(cfg))
        cfg = transformers.BertConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=1,
            num_attention_heads=4, max_position_embeddings=64)
        with pytest.raises(NotImplementedError, match="MaskedLM"):
            from_hf(transformers.BertModel(cfg))

    def test_distilbert_mlm(self):
        cfg = transformers.DistilBertConfig(
            vocab_size=128, dim=32, hidden_dim=64, n_layers=2, n_heads=4,
            max_position_embeddings=64)
        _check(transformers.DistilBertForMaskedLM(cfg), IDS)

    def test_internlm_out_proj_bias(self):
        """InternLM (trust_remote_code): llama layout + biases on all four
        attention projections. With o_proj bias zeroed the model must
        equal the qkv-bias-only import of the same weights; with it
        nonzero, logits must move — proving the bias lands on o_proj
        exactly and changes nothing else."""
        rng = np.random.RandomState(3)
        L, H, F, V = 2, 32, 64, 120

        def r(*shape):
            return rng.randn(*shape).astype(np.float32) * 0.05

        state = {"model.embed_tokens.weight": r(V, H),
                 "model.norm.weight": 1 + r(H), "lm_head.weight": r(V, H)}
        for i in range(L):
            for n in ("q", "k", "v", "o"):
                state[f"model.layers.{i}.self_attn.{n}_proj.weight"] = r(H, H)
                state[f"model.layers.{i}.self_attn.{n}_proj.bias"] = r(H)
            state[f"model.layers.{i}.input_layernorm.weight"] = 1 + r(H)
            state[f"model.layers.{i}.post_attention_layernorm.weight"] = 1 + r(H)
            state[f"model.layers.{i}.mlp.gate_proj.weight"] = r(F, H)
            state[f"model.layers.{i}.mlp.up_proj.weight"] = r(F, H)
            state[f"model.layers.{i}.mlp.down_proj.weight"] = r(H, F)

        class InternLMCfg:
            model_type = "internlm"
            vocab_size, hidden_size, intermediate_size = V, H, F
            num_hidden_layers, num_attention_heads = L, 4
            num_key_value_heads = 4
            max_position_embeddings = 64
            rms_norm_eps = 1e-6
            rope_theta = 10000.0
            tie_word_embeddings = False
            bias = True

        model, params = from_hf(dict(state), hf_config=InternLMCfg)
        assert model.config.attention_out_bias and model.config.attention_bias
        with_bias = _ours_logits(model, params, IDS)

        # zero the o bias -> must equal the qkv-bias-only (qwen2-style) import
        import copy
        p0 = copy.deepcopy(params)
        p0["model"]["layers"]["self_attn"]["o_proj"]["bias"][:] = 0.0
        zeroed = _ours_logits(model, p0, IDS)
        state_no_ob = {k: v for k, v in state.items()
                       if not k.endswith("o_proj.bias")}
        class QkvOnlyCfg(InternLMCfg):
            model_type = "qwen2"  # HF Qwen2: qkv bias, o_proj bias=False
        model2, params2 = from_hf(state_no_ob, hf_config=QkvOnlyCfg)
        assert not model2.config.attention_out_bias
        np.testing.assert_allclose(zeroed, _ours_logits(model2, params2, IDS),
                                   rtol=1e-5, atol=1e-5)
        assert np.abs(with_bias - zeroed).max() > 1e-3  # the bias is live

    def test_gpt_bigcode_mqa(self):
        """StarCoder family: fused [q(D), k(kv), v(kv)] c_attn with
        multi-query attention — exact logit parity."""
        cfg = transformers.GPTBigCodeConfig(
            vocab_size=128, n_embd=32, n_layer=2, n_head=4, n_positions=64,
            multi_query=True)
        _check(transformers.GPTBigCodeForCausalLM(cfg), IDS)

    def test_gpt_bigcode_mha_variant(self):
        cfg = transformers.GPTBigCodeConfig(
            vocab_size=128, n_embd=32, n_layer=2, n_head=4, n_positions=64,
            multi_query=False)
        _check(transformers.GPTBigCodeForCausalLM(cfg), IDS)

    def test_gpt_bigcode_untied_head(self):
        cfg = transformers.GPTBigCodeConfig(
            vocab_size=128, n_embd=32, n_layer=2, n_head=4, n_positions=64,
            multi_query=True, tie_word_embeddings=False)
        _check(transformers.GPTBigCodeForCausalLM(cfg), IDS)

    def test_phi3_fused_projections(self):
        """Phi-3 (4k variants: no rope scaling): fused qkv_proj and
        gate_up_proj split onto the llama layout — exact logit parity."""
        cfg = transformers.Phi3Config(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            pad_token_id=0)
        _check(transformers.Phi3ForCausalLM(cfg), IDS)

    def test_phi3_longrope_refused(self):
        cfg = transformers.Phi3Config(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
            original_max_position_embeddings=32, pad_token_id=0,
            rope_scaling={"type": "longrope",
                          "short_factor": [1.0] * 4, "long_factor": [2.0] * 4})
        hf = transformers.Phi3ForCausalLM(cfg)
        with pytest.raises(NotImplementedError, match="rope_scaling"):
            from_hf(hf)

    def test_mpt_alibi_no_bias(self):
        """MPT: ALiBi positions, bias-free projections, no-bias LN
        (imported as zero biases), fused Wqkv, exact erf-GeLU."""
        cfg = transformers.MptConfig(vocab_size=128, d_model=32, n_layers=2,
                                     n_heads=4, max_seq_len=64)
        _check(transformers.MptForCausalLM(cfg), IDS)

    def test_mpt_untied_head(self):
        cfg = transformers.MptConfig(vocab_size=128, d_model=32, n_layers=2,
                                     n_heads=4, max_seq_len=64,
                                     tie_word_embeddings=False)
        _check(transformers.MptForCausalLM(cfg), IDS)

    def test_mpt_non_pow2_heads_alibi_parity(self):
        """Non-power-of-two head counts exercise the two-geometric-series
        ALiBi slope formula — must still match HF exactly."""
        cfg = transformers.MptConfig(vocab_size=128, d_model=48, n_layers=2,
                                     n_heads=6, max_seq_len=64)
        _check(transformers.MptForCausalLM(cfg), IDS)

    def test_gpt_neo_unscaled_attention(self):
        """GPT-Neo: bias-free q/k/v, biased out_proj, NO 1/sqrt(d) softmax
        scale — exact logit parity against transformers."""
        cfg = transformers.GPTNeoConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
            intermediate_size=64, max_position_embeddings=64,
            attention_types=[[["global"], 2]])
        _check(transformers.GPTNeoForCausalLM(cfg), IDS)

    def test_gpt_neo_local_attention_window_gate(self):
        """Alternating local layers refuse without ignore_sliding_window;
        with it, logits are exact for sequences within the window."""
        cfg = transformers.GPTNeoConfig(
            vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
            intermediate_size=64, max_position_embeddings=64,
            attention_types=[[["global", "local"], 1]], window_size=16)
        hf = transformers.GPTNeoForCausalLM(cfg)
        hf.eval()
        with pytest.raises(NotImplementedError, match="local attention"):
            from_hf(hf)
        model, params = from_hf(hf, ignore_sliding_window=True)
        np.testing.assert_allclose(_ours_logits(model, params, IDS),
                                   _hf_logits(hf, IDS), **TOL)

    def test_qwen_v1_fused_qkv_layout(self):
        """Qwen v1 (trust_remote_code — not constructible via transformers):
        verify the fused c_attn split and the w1/w2 up-gate assignment
        structurally against the known-good unfused llama import, then run
        the imported model forward."""
        from deepspeed_tpu.module_inject.hf_import import (
            import_qwen, qwen_config_from_hf, import_llama)

        rng = np.random.RandomState(7)
        L, H, F2, V = 2, 32, 128, 120  # F2 = BOTH gated halves (Qwen convention)
        F = F2 // 2

        def r(*shape):
            return rng.randn(*shape).astype(np.float32) * 0.05

        qwen_state, llama_state = {}, {}
        qwen_state["transformer.wte.weight"] = llama_state["model.embed_tokens.weight"] = r(V, H)
        qwen_state["transformer.ln_f.weight"] = llama_state["model.norm.weight"] = r(H)
        qwen_state["lm_head.weight"] = llama_state["lm_head.weight"] = r(V, H)
        for i in range(L):
            q, k, v = r(H, H), r(H, H), r(H, H)
            qb, kb, vb = r(H), r(H), r(H)
            qwen_state[f"transformer.h.{i}.attn.c_attn.weight"] = np.concatenate([q, k, v])
            qwen_state[f"transformer.h.{i}.attn.c_attn.bias"] = np.concatenate([qb, kb, vb])
            for n, w, b in (("q", q, qb), ("k", k, kb), ("v", v, vb)):
                llama_state[f"model.layers.{i}.self_attn.{n}_proj.weight"] = w
                llama_state[f"model.layers.{i}.self_attn.{n}_proj.bias"] = b
            o = r(H, H)
            qwen_state[f"transformer.h.{i}.attn.c_proj.weight"] = o
            llama_state[f"model.layers.{i}.self_attn.o_proj.weight"] = o
            ln1, ln2 = r(H), r(H)
            qwen_state[f"transformer.h.{i}.ln_1.weight"] = ln1
            qwen_state[f"transformer.h.{i}.ln_2.weight"] = ln2
            llama_state[f"model.layers.{i}.input_layernorm.weight"] = ln1
            llama_state[f"model.layers.{i}.post_attention_layernorm.weight"] = ln2
            up, gate, down = r(F, H), r(F, H), r(H, F)
            qwen_state[f"transformer.h.{i}.mlp.w1.weight"] = up      # w1 = up
            qwen_state[f"transformer.h.{i}.mlp.w2.weight"] = gate    # w2 = gate (SiLU side)
            qwen_state[f"transformer.h.{i}.mlp.c_proj.weight"] = down
            llama_state[f"model.layers.{i}.mlp.up_proj.weight"] = up
            llama_state[f"model.layers.{i}.mlp.gate_proj.weight"] = gate
            llama_state[f"model.layers.{i}.mlp.down_proj.weight"] = down

        class QwenCfg:
            model_type = "qwen"
            vocab_size, hidden_size, intermediate_size = V, H, F2
            num_hidden_layers, num_attention_heads = L, 4
            kv_channels = H // 4
            seq_length = 64
            layer_norm_epsilon = 1e-6
            rotary_emb_base = 10000.0
            no_bias = True

        class LlamaCfg:
            model_type = "llama"
            vocab_size, hidden_size, intermediate_size = V, H, F
            num_hidden_layers, num_attention_heads = L, 4
            num_key_value_heads = 4
            max_position_embeddings = 64
            rms_norm_eps = 1e-6
            rope_theta = 10000.0
            tie_word_embeddings = False
            attention_bias = True

        got = import_qwen(qwen_state, QwenCfg)
        want = import_llama(llama_state, LlamaCfg)
        jax.tree.map(np.testing.assert_array_equal, got, want)

        cfg = qwen_config_from_hf(QwenCfg)
        assert cfg.intermediate_size == F and cfg.num_key_value_heads == 4
        model, params = from_hf(qwen_state, hf_config=QwenCfg)
        logits = _ours_logits(model, params, IDS)
        assert np.isfinite(logits).all() and logits.shape == (2, 12, V)

    def test_engine_trains_imported_model(self):
        """The imported (model, params) drop straight into initialize()."""
        import deepspeed_tpu
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64)
        model, params = from_hf(transformers.LlamaForCausalLM(cfg))
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_batch_size": 8, "train_micro_batch_size_per_gpu": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3}})
        ids = np.random.RandomState(0).randint(0, 128, size=(8, 16)).astype(np.int32)
        losses = [float(engine.train_batch(batch=(jnp.asarray(ids), jnp.asarray(ids))))
                  for _ in range(3)]
        assert all(np.isfinite(l) for l in losses) and losses[-1] < losses[0]
