# Shared sys.path bootstrap for the uninstalled bins, exec'd by each
# `bin/<tool>` (it cannot be IMPORTED — the whole point is that the repo
# root is not importable yet; __file__ under exec is the CALLING bin's
# path, symlink-resolved below). `python bin/<tool>` puts bin/ (not the
# repo root) on sys.path; this inserts the real repo root and exports it
# on PYTHONPATH so launcher worker subprocesses
# (`python -m deepspeed_tpu...`) and remote launches inherit it too.
import os as _os
import sys as _sys

_root = _os.path.dirname(_os.path.dirname(_os.path.realpath(__file__)))
_sys.path.insert(0, _root)
_os.environ["PYTHONPATH"] = (_root + _os.pathsep + _os.environ["PYTHONPATH"]
                             if _os.environ.get("PYTHONPATH") else _root)
