"""Developer tooling (not shipped with the package)."""
