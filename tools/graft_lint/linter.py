"""The graft-lint analysis core: pure-AST, no jax import.

Each rule family is a method suite on :class:`FileLinter`; a file is
parsed once and every rule walks the same tree. Violations carry a
``symbol`` (dotted class.function scope) so baseline entries survive
line-number drift.
"""

import ast
import json
import os
from collections import namedtuple

# Rule ids ----------------------------------------------------------------
JIT_PURITY = "jit-purity"
HOST_SYNC = "host-sync"
THREAD_SHARED = "thread-shared-state"
SPEC_CONSISTENCY = "spec-consistency"
ENV_REGISTRY = "env-registry"
RULES = (JIT_PURITY, HOST_SYNC, THREAD_SHARED, SPEC_CONSISTENCY,
         ENV_REGISTRY)

# Must mirror deepspeed_tpu/parallel/topology.py MESH_AXES — the linter
# cannot import the package (no jax at lint time); a unit test asserts
# the two stay in sync.
MESH_AXES = ("pipe", "data", "expert", "sequence", "tensor")

Violation = namedtuple("Violation", "rule path line col symbol message")

# ------------------------------------------------------------------ config
# Names whose call wraps a function for tracing (the first positional
# argument, or the decorated function).
_JIT_WRAPPERS = {"jit", "pjit", "shard_map", "pallas_call",
                 "shard_map_kernel", "maybe_checkify_jit", "checkify"}

# host-sync scope: file suffix -> traced-hot-path qualnames. These are
# the serving paths where one stray sync serializes the pipeline.
_HOT_PATHS = {
    "inference/v2/scheduler.py": {
        "DynamicSplitFuseScheduler._plan",
        "DynamicSplitFuseScheduler._try_burst",
        "DynamicSplitFuseScheduler.step",
    },
    "serving/gateway.py": {
        "ServingGateway._pump_once",
        "ServingGateway._admit",
        "ServingGateway._step",
        "ServingGateway._process_cancels",
        "ServingGateway._process_deadlines",
        "ServingGateway._resume_paused",
        "ServingGateway._on_token",
    },
    "inference/v2/engine_v2.py": {
        "InferenceEngineV2.put",
        "InferenceEngineV2.decode_burst",
    },
}

# Calls that force a device→host sync (or a host copy of device data).
_SYNC_ATTRS = {"item", "block_until_ready"}
_SYNC_DOTTED = {"jax.device_get", "jax.block_until_ready",
                "np.asarray", "np.array", "numpy.asarray", "numpy.array"}
# float()/bool() on an array force a sync; int() is deliberately NOT
# flagged — the hot paths do int() on host-side allocator bookkeeping
# constantly, and int() on a device array shows up via the np.* /
# .item() patterns above anyway.
_SYNC_BUILTINS = {"float", "bool"}

# thread-shared-state registry: class -> attributes mutated by more
# than one thread. Writes outside ``with self.<*lock*>:`` are flagged
# (``__init__`` is exempt — the object is not yet published).
THREAD_SHARED_REGISTRY = {
    "ServingGateway": {"_cancels", "_state", "_pump_stop", "_handoffs"},
    "NebulaCheckpointService": {"_pending_job", "_failure", "_last_persist",
                                "_stats", "_thread"},
    "MonitorMaster": {"backends"},
    "ServingMetrics": {"_counters", "_gauges", "_external"},
    "BlockedAllocator": {"_free", "_free_set"},
    "PrefixCacheManager": {"_leases", "lookups", "hits", "tokens_saved",
                           "insertions", "tier", "tier2_hits",
                           "tier2_tokens_saved"},
    # kv tier: the prefetch worker stages/claims against state the pump
    # thread (demote/promote) and client threads (prefetch kick, stats)
    # also mutate
    "TierManager": {"_staged", "_inflight", "demoted_blocks",
                    "promoted_blocks", "prefetched_blocks", "stage_hits",
                    "prefetch_waits", "prefetch_wait_ms",
                    "prefetch_timeouts", "prefetch_errors",
                    "quant_error_max", "exported_blocks", "imported_blocks",
                    "import_rejects"},
    "HostKVStore": {"_records", "bytes_resident", "demotions", "promotions",
                    "evictions", "lookups", "hits"},
    # spec decode: the gateway pump drafts/notes while client threads
    # reach forget() through engine.flush (cancel / deadline / drain)
    "SpecDecodeState": {"_ema", "_disabled", "steps", "accepted", "drafted",
                        "emitted", "disables"},
    # fleet: relay threads + heartbeat thread + client threads all touch
    # router/health/replica state
    "FleetRouter": {"_counters", "_relays", "_closed"},
    "ReplicaHealth": {"_state", "_consecutive_failures", "_half_open_ok",
                      "_next_probe_at", "_probe_backoff", "transitions"},
    "GatewayReplica": {"gateway", "restarts"},
    "FaultyReplica": {"_killed", "_reject_left", "_submits"},
    # disagg serving: relay threads publish/claim handoffs and note
    # pool outcomes concurrently; the router snapshot reads both
    "HandoffManager": {"_inflight", "published", "delivered", "acked",
                       "failed", "expired"},
    "PoolScheduler": {"mode", "_consecutive_failures",
                      "_consecutive_successes", "_requests_while_degraded",
                      "degraded_entries", "degraded_exits", "transitions"},
    # preemption: the signal handler and the training thread race on the
    # request flag; the heartbeat is beaten from the training thread and
    # read by the agent process (file) but its bookkeeping is shared
    # with any in-process watchdog probes
    "PreemptionGuard": {"_requested", "_requested_at"},
    "HeartbeatWriter": {"_last_step", "_last_beat_t"},
    # grouped GEMM dispatch telemetry: serving traces from gateway pump
    # threads while bench/test readers snapshot from the main thread
    "GroupedGemmStats": {"_counts"},
}

_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "update", "add", "discard", "setdefault", "popitem",
             "difference_update", "appendleft"}

# spec-consistency dtype-leak scope (fp32 Python constants materialized
# as arrays in bf16 arithmetic): kernel and model code only (plus the
# grouped-GEMM dispatch, which sits one level up from ops/pallas but
# builds the kernel's padded layouts in the activation dtype).
_DTYPE_DIRS = ("ops/pallas/", "models/", "ops/grouped_gemm")
_JNP_CTORS = {"jnp.array": 2, "jnp.asarray": 2, "jnp.ones": 2,
              "jnp.zeros": 2, "jnp.full": 3}  # value -> positional arity
#  with dtype


# ----------------------------------------------------------------- helpers
def _dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last(dotted):
    return dotted.rsplit(".", 1)[-1] if dotted else None


def _self_attr(node):
    """'attr' when node is ``self.attr`` (unwrapping subscripts:
    ``self.attr[k]`` → 'attr'), else None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _has_float_literal(node):
    """True when node is/contains a non-bool float constant (the thing
    that silently materializes as fp32)."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, float)
    if isinstance(node, ast.UnaryOp):
        return _has_float_literal(node.operand)
    if isinstance(node, (ast.List, ast.Tuple)):
        return any(_has_float_literal(e) for e in node.elts)
    return False


def _parse_pragmas(source):
    """line -> set of disabled rule names ('all' disables everything).
    A pragma on its own line applies to the next line too."""
    pragmas = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        idx = text.find("# ds-lint:")
        if idx < 0:
            continue
        body = text[idx + len("# ds-lint:"):]
        body = body.split("--", 1)[0]  # strip the reason
        body = body.strip()
        if not body.startswith("disable="):
            continue
        rules = {r.strip() for r in body[len("disable="):].split(",") if r.strip()}
        pragmas.setdefault(lineno, set()).update(rules)
        if text[:idx].strip() == "":  # standalone pragma line
            pragmas.setdefault(lineno + 1, set()).update(rules)
    return pragmas


def load_baseline(path):
    """tools/graft_lint/baseline.json → set of (rule, path, symbol)
    triples. Line numbers are deliberately not part of the key."""
    with open(path) as fd:
        data = json.load(fd)
    if data.get("version") != 1:
        raise ValueError(f"unsupported baseline version in {path}")
    return {(e["rule"], e["path"], e.get("symbol", "")) for e in
            data.get("suppressions", ())}


# --------------------------------------------------------------- the pass
class FileLinter:

    def __init__(self, path, source, relpath=None):
        self.path = path
        # rule scoping matches on /-separated relative paths
        self.relpath = (relpath or path).replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.violations = []
        # parent / scope bookkeeping filled by _annotate
        self._parents = {}
        self._qualnames = {}
        self._traced = set()  # FunctionDef/Lambda nodes traced by jit
        self._annotate()

    # -- tree annotation ---------------------------------------------------
    def _annotate(self):
        defs_by_name = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)
        # dotted scope names
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                parts = [node.name]
                p = self._parents.get(node)
                while p is not None:
                    if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                        parts.append(p.name)
                    p = self._parents.get(p)
                self._qualnames[node] = ".".join(reversed(parts))

        # traced functions: decorated with a jit wrapper, or passed as
        # the first argument to one
        roots = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if _last(_dotted(target)) in _JIT_WRAPPERS:
                        roots.add(node)
            if isinstance(node, ast.Call) and \
                    _last(_dotted(node.func)) in _JIT_WRAPPERS and node.args:
                wrapped = node.args[0]
                if isinstance(wrapped, ast.Lambda):
                    roots.add(wrapped)
                elif isinstance(wrapped, ast.Name):
                    for d in defs_by_name.get(wrapped.id, ()):
                        roots.add(d)
        # everything defined inside a traced function traces with it
        for root in roots:
            for sub in ast.walk(root):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    self._traced.add(sub)
        self._traced |= roots
        self._traced_roots = roots

    def _qualname(self, node):
        return self._qualnames.get(node, "<module>")

    def _enclosing_symbol(self, node):
        p = node
        while p is not None:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                return self._qualname(p)
            p = self._parents.get(p)
        return "<module>"

    def _emit(self, rule, node, message):
        self.violations.append(Violation(
            rule=rule, path=self.relpath, line=node.lineno,
            col=getattr(node, "col_offset", 0),
            symbol=self._enclosing_symbol(node), message=message))

    # -- rule 1: jit-purity ------------------------------------------------
    def check_jit_purity(self):
        for fn in self._traced:
            # Only the ROOT traced function's params are definitely
            # tracers. Nested-def params are often static metadata bound
            # through jax.tree.map (partition dims, config), so the
            # branch check stays root-only; side-effect checks apply to
            # the whole traced subtree.
            params = set()
            if fn in self._traced_roots:
                args = fn.args
                for a in (args.posonlyargs + args.args + args.kwonlyargs
                          + ([args.vararg] if args.vararg else [])
                          + ([args.kwarg] if args.kwarg else [])):
                    params.add(a.arg)
                params.discard("self")
            for node in ast.walk(fn):
                if node is fn:
                    continue
                # nested defs/lambdas are traced too and get their own
                # iteration — only check nodes fn directly owns
                if self._owner_fn(node) is not fn:
                    continue
                self._check_purity_node(fn, node, params)

    def _owner_fn(self, node):
        p = self._parents.get(node)
        while p is not None:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return p
            p = self._parents.get(p)
        return None

    def _check_purity_node(self, fn, node, params):
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            root = dotted.split(".", 1)[0] if dotted else None
            if root in ("time", "random") or (
                    dotted and dotted.startswith(("np.random.",
                                                  "numpy.random."))):
                self._emit(JIT_PURITY, node,
                           f"call to {dotted}() inside a traced function "
                           f"runs at TRACE time only (or reorders under "
                           f"compilation) — hoist it out of the jitted "
                           f"region")
            elif dotted == "print":
                self._emit(JIT_PURITY, node,
                           "print() inside a traced function fires at "
                           "trace time only; use jax.debug.print")
            elif dotted == "os.getenv":
                self._emit(JIT_PURITY, node,
                           "os.getenv inside a traced function is a "
                           "trace-time constant; read it before tracing")
        if isinstance(node, ast.Attribute) and \
                _dotted(node) == "os.environ":
            self._emit(JIT_PURITY, node,
                       "os.environ inside a traced function is a "
                       "trace-time constant; read it before tracing")
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                    if _self_attr(el) is not None:
                        self._emit(JIT_PURITY, node,
                                   f"mutation of self.{_self_attr(el)} "
                                   f"inside a traced function happens at "
                                   f"trace time, not per call")
        if isinstance(node, (ast.If, ast.While)):
            if self._branches_on_param(node.test, params):
                kind = "if" if isinstance(node, ast.If) else "while"
                self._emit(JIT_PURITY, node,
                           f"Python `{kind}` on a traced argument forces "
                           f"concretization (TracerBoolConversionError at "
                           f"runtime); use lax.cond/jnp.where")

    def _branches_on_param(self, test, params):
        """Bare-name truthiness / value comparison on a traced parameter.
        Identity and containment checks (``is None``, ``in``) are static
        pytree-structure tests and stay legal."""
        if isinstance(test, ast.Name):
            return test.id in params
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._branches_on_param(test.operand, params)
        if isinstance(test, ast.BoolOp):
            return any(self._branches_on_param(v, params) for v in test.values)
        if isinstance(test, ast.Compare):
            if any(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in test.ops):
                return False
            return any(isinstance(e, ast.Name) and e.id in params
                       for e in [test.left] + test.comparators)
        return False

    # -- rule 2: host-sync -------------------------------------------------
    def check_host_sync(self):
        hot = None
        for suffix, names in _HOT_PATHS.items():
            if self.relpath.endswith(suffix):
                hot = names
                break
        if hot is None:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if self._qualname(node) not in hot:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func)
                if isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in _SYNC_ATTRS:
                    self._emit(HOST_SYNC, sub,
                               f".{sub.func.attr}() in a serving hot path "
                               f"blocks on the device — keep this path "
                               f"async")
                elif dotted in _SYNC_DOTTED:
                    self._emit(HOST_SYNC, sub,
                               f"{dotted}() in a serving hot path copies "
                               f"device data to host (implicit sync)")
                elif dotted in _SYNC_BUILTINS and sub.args and isinstance(
                        sub.args[0], (ast.Name, ast.Attribute, ast.Subscript)):
                    self._emit(HOST_SYNC, sub,
                               f"{dotted}() on an array in a serving hot "
                               f"path forces a device sync")

    # -- rule 3: thread-shared-state --------------------------------------
    def check_thread_shared(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = THREAD_SHARED_REGISTRY.get(node.name)
            if not attrs:
                continue
            for method in node.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__":
                    continue  # not yet published to other threads
                self._check_method_writes(method, attrs)

    def _check_method_writes(self, method, attrs):
        for node in ast.walk(method):
            written = None
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                        a = _self_attr(el)
                        if a in attrs:
                            written = a
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                a = _self_attr(node.func.value)
                if a in attrs:
                    written = a
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    a = _self_attr(t)
                    if a in attrs:
                        written = a
            if written is not None and not self._under_lock(node):
                self._emit(THREAD_SHARED, node,
                           f"write to shared self.{written} outside a "
                           f"`with self.<lock>:` block "
                           f"(class is touched by multiple threads)")

    def _under_lock(self, node):
        p = self._parents.get(node)
        while p is not None:
            if isinstance(p, ast.With):
                for item in p.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Call):
                        ctx = ctx.func  # e.g. self._lock.acquire_timeout()
                    d = _dotted(ctx)
                    if d and d.startswith("self.") and "lock" in d.lower():
                        return True
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break  # don't credit an outer function's lock
            p = self._parents.get(p)
        return False

    # -- rule 4: spec-consistency ------------------------------------------
    def check_spec_consistency(self):
        spec_ctors = {"PartitionSpec"}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "PartitionSpec" and alias.asname:
                        spec_ctors.add(alias.asname)
        allowed = set(MESH_AXES)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _last(_dotted(node.func))
            if name in spec_ctors:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    for el in (arg.elts if isinstance(arg, (ast.Tuple,
                                                            ast.List))
                               else [arg]):
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str) and \
                                el.value not in allowed:
                            self._emit(SPEC_CONSISTENCY, el,
                                       f"PartitionSpec axis {el.value!r} is "
                                       f"not a declared mesh axis "
                                       f"{MESH_AXES}")
            if any(self.relpath.rpartition("deepspeed_tpu/")[2]
                   .startswith(d) for d in _DTYPE_DIRS):
                dotted = _dotted(node.func)
                arity = _JNP_CTORS.get(dotted)
                if arity is not None and len(node.args) < arity and \
                        not any(kw.arg == "dtype" for kw in node.keywords):
                    value_args = node.args[-1:] if dotted == "jnp.full" \
                        else node.args[:1]
                    if any(_has_float_literal(a) for a in value_args):
                        self._emit(SPEC_CONSISTENCY, node,
                                   f"{dotted}() on a float literal without "
                                   f"dtype= materializes fp32 and promotes "
                                   f"bf16 arithmetic — pass dtype explicitly")

    # -- rule 5: env-registry ----------------------------------------------
    def check_env_registry(self):
        if self.relpath.endswith("utils/env_registry.py"):
            return
        for node in ast.walk(self.tree):
            key = None
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted in ("os.environ.get", "os.getenv") and node.args:
                    key = node.args[0]
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and \
                    _dotted(node.value) == "os.environ":
                key = node.slice
            elif isinstance(node, ast.Compare) and \
                    len(node.ops) == 1 and \
                    isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                    _dotted(node.comparators[0]) == "os.environ":
                key = node.left
            if isinstance(key, ast.Constant) and \
                    isinstance(key.value, str) and \
                    key.value.startswith("DS_"):
                self._emit(ENV_REGISTRY, node,
                           f"read of {key.value} bypasses "
                           f"deepspeed_tpu/utils/env_registry.py — use "
                           f"env_bool/env_int/env_str/env_raw")

    # -- driver ------------------------------------------------------------
    def run(self):
        self.check_jit_purity()
        self.check_host_sync()
        self.check_thread_shared()
        self.check_spec_consistency()
        self.check_env_registry()
        pragmas = _parse_pragmas(self.source)
        kept = []
        for v in self.violations:
            disabled = pragmas.get(v.line, ())
            if v.rule in disabled or "all" in disabled:
                continue
            kept.append(v)
        kept.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
        return kept


def lint_file(path, source=None, relpath=None):
    """All unsuppressed-by-pragma violations for one file."""
    if source is None:
        with open(path) as fd:
            source = fd.read()
    return FileLinter(path, source, relpath=relpath).run()


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def lint_paths(paths, baseline=None, root=None):
    """Lint every .py file under ``paths``. → (violations, baselined)
    where ``baselined`` counts suppressions consumed from the baseline
    set of (rule, relpath, symbol) triples."""
    baseline = baseline or set()
    root = root or os.getcwd()
    violations, baselined = [], 0
    for path in _iter_py_files(paths):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        for v in lint_file(path, relpath=rel):
            if (v.rule, v.path, v.symbol) in baseline:
                baselined += 1
                continue
            violations.append(v)
    return violations, baselined
