"""The graft-lint analysis core: pure-AST, no jax import.

Each rule family is a method suite on :class:`FileLinter`; a file is
parsed once and every rule walks the same tree. Violations carry a
``symbol`` (dotted class.function scope) so baseline entries survive
line-number drift.
"""

import ast
import json
import os
from collections import namedtuple

# Rule ids ----------------------------------------------------------------
JIT_PURITY = "jit-purity"
HOST_SYNC = "host-sync"
THREAD_SHARED = "thread-shared-state"
SPEC_CONSISTENCY = "spec-consistency"
ENV_REGISTRY = "env-registry"
LOCK_ORDER_RULE = "lock-order"
KNOB_DOCS = "knob-docs"  # cross-artifact rule, driven by cli.check_knob_docs
WIRE_CONTRACT = "wire-contract"  # cross-file parity over the process boundary
REPLAY_DETERMINISM = "replay-determinism"
RULES = (JIT_PURITY, HOST_SYNC, THREAD_SHARED, SPEC_CONSISTENCY,
         ENV_REGISTRY, LOCK_ORDER_RULE, KNOB_DOCS, WIRE_CONTRACT,
         REPLAY_DETERMINISM)

# Must mirror deepspeed_tpu/parallel/topology.py MESH_AXES — the linter
# cannot import the package (no jax at lint time); a unit test asserts
# the two stay in sync.
MESH_AXES = ("pipe", "data", "expert", "sequence", "tensor")

Violation = namedtuple("Violation", "rule path line col symbol message")

# ------------------------------------------------------------------ config
# Names whose call wraps a function for tracing (the first positional
# argument, or the decorated function).
_JIT_WRAPPERS = {"jit", "pjit", "shard_map", "pallas_call",
                 "shard_map_kernel", "maybe_checkify_jit", "checkify"}

# host-sync scope: file suffix -> traced-hot-path qualnames. These are
# the serving paths where one stray sync serializes the pipeline.
_HOT_PATHS = {
    "inference/v2/scheduler.py": {
        "DynamicSplitFuseScheduler._plan",
        "DynamicSplitFuseScheduler._try_burst",
        "DynamicSplitFuseScheduler._try_spec_burst",
        "DynamicSplitFuseScheduler.step",
        # pipelined (DS_ASYNC_BURST) pump: a stray sync here stalls the
        # double buffer — the ONE intended sync lives in
        # AsyncBurstHandle.fetch, reached via _fence_one
        "DynamicSplitFuseScheduler._plan_async_k",
        "DynamicSplitFuseScheduler._accept_async",
        "DynamicSplitFuseScheduler._fence_one",
        "DynamicSplitFuseScheduler._drain_pipeline",
        "DynamicSplitFuseScheduler._continue_pipeline",
        "DynamicSplitFuseScheduler._try_async_start",
    },
    "serving/gateway.py": {
        "ServingGateway._pump_once",
        "ServingGateway._admit",
        "ServingGateway._step",
        "ServingGateway._process_cancels",
        "ServingGateway._process_deadlines",
        "ServingGateway._resume_paused",
        "ServingGateway._on_token",
    },
    "inference/v2/engine_v2.py": {
        "InferenceEngineV2.put",
        "InferenceEngineV2.decode_burst",
        "InferenceEngineV2.decode_burst_async",
        "InferenceEngineV2.verify_burst",
        "AsyncBurstHandle.fetch",
    },
}

# Calls that force a device→host sync (or a host copy of device data).
_SYNC_ATTRS = {"item", "block_until_ready"}
_SYNC_DOTTED = {"jax.device_get", "jax.block_until_ready",
                "np.asarray", "np.array", "numpy.asarray", "numpy.array"}
# float()/bool() on an array force a sync; int() is deliberately NOT
# flagged — the hot paths do int() on host-side allocator bookkeeping
# constantly, and int() on a device array shows up via the np.* /
# .item() patterns above anyway.
_SYNC_BUILTINS = {"float", "bool"}

# thread-shared-state registry: class -> attributes mutated by more
# than one thread. Writes outside ``with self.<*lock*>:`` are flagged
# (``__init__`` is exempt — the object is not yet published).
THREAD_SHARED_REGISTRY = {
    "ServingGateway": {"_cancels", "_state", "_pump_stop", "_handoffs",
                       "_pending_refresh"},
    "NebulaCheckpointService": {"_pending_job", "_failure", "_last_persist",
                                "_stats", "_thread"},
    "MonitorMaster": {"backends"},
    "ServingMetrics": {"_counters", "_gauges", "_external"},
    "BlockedAllocator": {"_free", "_free_set"},
    "PrefixCacheManager": {"_leases", "lookups", "hits", "tokens_saved",
                           "insertions", "tier", "tier2_hits",
                           "tier2_tokens_saved"},
    # kv tier: the prefetch worker stages/claims against state the pump
    # thread (demote/promote) and client threads (prefetch kick, stats)
    # also mutate
    "TierManager": {"_staged", "_inflight", "demoted_blocks",
                    "promoted_blocks", "prefetched_blocks", "stage_hits",
                    "prefetch_waits", "prefetch_wait_ms",
                    "prefetch_timeouts", "prefetch_errors",
                    "quant_error_max", "exported_blocks", "imported_blocks",
                    "import_rejects"},
    "HostKVStore": {"_records", "bytes_resident", "demotions", "promotions",
                    "evictions", "lookups", "hits"},
    # multi-tenant LoRA: the adapter prefetch worker stages slabs while
    # the pump thread binds/promotes/evicts and client threads register,
    # publish, prefetch-kick, and read stats
    "AdapterStore": {"_hot", "_slot_meta", "_refs", "_uid_slot", "_lru",
                     "_free", "_host", "_host_bytes", "_staged", "_inflight",
                     "_a", "_b", "_scales", "_shutdown",
                     "registrations", "promotions", "evictions",
                     "host_evictions", "hot_hits", "hot_misses", "swaps",
                     "prefetched", "stage_hits", "prefetch_errors",
                     "publish_rejects"},
    # structured decoding: every gateway's client submit threads compile
    # schemas through the ONE process-wide cache at admission, so the
    # LRU map and its counters are cross-thread state
    "SchemaCompilerCache": {"_cache", "compiles", "hits"},
    # spec decode: the gateway pump drafts/notes while client threads
    # reach forget() through engine.flush (cancel / deadline / drain),
    # and the online SLO controller adjusts draft_len_cfg live
    "SpecDecodeState": {"_ema", "_disabled", "steps", "accepted", "drafted",
                        "emitted", "disables", "draft_len_cfg"},
    # serving autotuner: the controller thread mutates decision state
    # while operator threads read stats()/reset(); the trace recorder
    # is written from every client thread that submits
    "OnlineSLOController": {"_breach", "_clear", "_cooldown", "_frozen",
                            "_last_action", "_clear_required",
                            "_last_up_tick", "ticks", "adjustments",
                            "rollbacks"},
    "TraceRecorder": {"_t0", "_requests", "_groups", "recorded"},
    # fleet: relay threads + heartbeat thread + client threads all touch
    # router/health/replica state
    "FleetRouter": {"_counters", "_relays", "_closed"},
    # wire transport: the supervisor monitor thread relaunches children
    # while operator threads kill/stop/query; the client's reader thread
    # demuxes into state client threads register/release; the server's
    # accept/dispatch/relay threads share conn + stream registries
    "FleetSupervisor": {"_children", "_stopped", "restarts_total"},
    "WireReplica": {"_sock", "_wfile", "_reader", "_pending", "_next_rid",
                    "_backoff", "_retry_at", "_closed", "reconnects"},
    "ReplicaServer": {"_state", "_conns", "_streams", "served"},
    "ReplicaHealth": {"_state", "_consecutive_failures", "_half_open_ok",
                      "_next_probe_at", "_probe_backoff", "transitions"},
    "GatewayReplica": {"gateway", "restarts"},
    "FaultyReplica": {"_killed", "_reject_left", "_submits",
                      "_claimed_version"},
    # live weight refresh: rollouts run on an operator/train thread
    # while relay threads read versions and the publisher may be shared
    # with a bench/train loop publishing concurrently
    "WeightPublisher": {"publishes", "rejects"},
    "FleetRefreshController": {"current_version", "current_chain",
                               "_adopted_params", "rollouts"},
    # disagg serving: relay threads publish/claim handoffs and note
    # pool outcomes concurrently; the router snapshot reads both
    "HandoffManager": {"_inflight", "published", "delivered", "acked",
                       "failed", "expired"},
    "PoolScheduler": {"mode", "_consecutive_failures",
                      "_consecutive_successes", "_requests_while_degraded",
                      "degraded_entries", "degraded_exits", "transitions"},
    # preemption: the signal handler and the training thread race on the
    # request flag; the heartbeat is beaten from the training thread and
    # read by the agent process (file) but its bookkeeping is shared
    # with any in-process watchdog probes
    "PreemptionGuard": {"_requested", "_requested_at"},
    "HeartbeatWriter": {"_last_step", "_last_beat_t"},
    # grouped GEMM dispatch telemetry: serving traces from gateway pump
    # threads while bench/test readers snapshot from the main thread
    "GroupedGemmStats": {"_counts"},
}

_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "update", "add", "discard", "setdefault", "popitem",
             "difference_update", "appendleft"}

# lock-order: the canonical acquisition order, as CODE. A lock may only
# be taken while holding locks of strictly LOWER rank; an edge from a
# higher rank to a lower one is a deadlock-shaped inversion. The two
# documented orders this encodes: router -> gateway -> engine-side
# caches, and the kv-tier stack ``manager._lock -> tier._lock ->
# store._lock`` (tier_manager.py module docstring). Locks not listed
# here are "unranked": edges touching them are still collected and
# checked for cycles, just not against a rank.
LOCK_ORDER = {
    # the refresh controller orchestrates ABOVE the router (it calls
    # router counters/health and replica refresh while holding its
    # lock), and calls into its publisher, so both rank below rank 10
    "FleetRefreshController._lock": 4,
    "WeightPublisher._lock": 6,
    # the fleet supervisor is an outermost orchestrator: its monitor
    # thread only spawns/kills OS processes and never calls into the
    # router, but operator code may stop the fleet while holding no
    # other lock — rank it above (outside) the router
    "FleetSupervisor._lock": 8,
    "FleetRouter._lock": 10,
    # the wire client is called FROM router relay threads (rank 10) and
    # itself takes only its own lock (socket I/O happens outside it)
    "WireReplica._lock": 12,
    "HandoffManager._lock": 14,
    "PoolScheduler._lock": 16,
    # the replica server dispatches into the gateway (ranks 20+) while
    # holding nothing; its own lock guards only conn/stream registries
    "ReplicaServer._lock": 17,
    # the online SLO controller decides under its own lock and actuates
    # gateway knobs outside it, so it ranks between the router and the
    # gateway's own locks; the trace recorder is a leaf (submit-path
    # append, never holds anything else)
    "OnlineSLOController._lock": 18,
    "TraceRecorder._lock": 19,
    "ServingGateway._handoff_lock": 20,
    "ServingGateway._cancel_lock": 22,
    "ServingGateway._state_lock": 24,
    # staged-refresh handshake: always held alone on the caller side;
    # the pump takes it strictly before/after (never around) the swap
    "ServingGateway._refresh_lock": 26,
    "PrefixCacheManager._lock": 30,
    # the adapter store is called from the pump with no engine-side lock
    # held above it, and itself calls only its publisher (unranked leaf
    # I/O) — it slots between the prefix cache and the kv-tier stack
    "AdapterStore._lock": 34,
    # the schema compiler cache is a leaf: get_or_compile runs the
    # compiler OUTSIDE the lock and the locked sections touch only the
    # LRU map — it never calls into another registered class
    "SchemaCompilerCache._lock": 36,
    "TierManager._lock": 40,
    "HostKVStore._lock": 50,
}

# lock-order: which self-attributes point at OTHER registered classes,
# so ``with self.manager._lock:`` / ``mgr = self.manager; with
# mgr._lock:`` and ``self.tier.demote(...)`` resolve to the peer
# class's locks one call level deep.
CROSS_REFS = {
    "PrefixCacheManager": {"tier": "TierManager"},
    "TierManager": {"manager": "PrefixCacheManager", "store": "HostKVStore"},
    "FleetRouter": {"handoffs": "HandoffManager", "pools": "PoolScheduler"},
    "FleetRefreshController": {"router": "FleetRouter",
                               "publisher": "WeightPublisher"},
    "OnlineSLOController": {"gateway": "ServingGateway"},
}

# lock-order: per registered class, the methods a PEER may call and the
# lock keys each acquires (its own and, one level deep, the locks of
# the objects it calls into). A cross-object call into one of these
# while holding a lock contributes acquisition edges. The table is kept
# honest by an in-file drift check (run only on the class's home file,
# LOCKING_METHODS_HOME): a declared method that no longer exists, a
# direct self-lock acquisition it fails to declare, or a new public
# locking method missing from the table are all lock-order violations.
LOCKING_METHODS = {
    "TierManager": {
        "demote": ("TierManager._lock", "HostKVStore._lock"),
        "probe_chain": ("TierManager._lock", "HostKVStore._lock"),
        "claim": ("TierManager._lock", "HostKVStore._lock"),
        "unclaim": ("HostKVStore._lock",),
        "note_promoted": ("TierManager._lock",),
        "export_chain": ("PrefixCacheManager._lock", "TierManager._lock"),
        "import_chain": ("TierManager._lock", "HostKVStore._lock"),
        "invalidate": ("TierManager._lock", "HostKVStore._lock"),
        "prefetch": ("TierManager._lock", "TierManager._queue_ready"),
        "wait_prefetch": ("TierManager._lock",),
        "shutdown": ("TierManager._queue_ready", "TierManager._lock",
                     "HostKVStore._lock"),
        "stats": ("TierManager._lock", "HostKVStore._lock"),
    },
    "HostKVStore": {
        "put": ("HostKVStore._lock",),
        "pop": ("HostKVStore._lock",),
        "peek": ("HostKVStore._lock",),
        "contains": ("HostKVStore._lock",),
        "clear": ("HostKVStore._lock",),
        "stats": ("HostKVStore._lock",),
    },
    "PrefixCacheManager": {
        "attach_tier": ("PrefixCacheManager._lock",),
        "ensure_free": ("PrefixCacheManager._lock",),
        "reserve": ("PrefixCacheManager._lock",),
        "acquire": ("PrefixCacheManager._lock", "TierManager._lock",
                    "HostKVStore._lock"),
        "match_len": ("PrefixCacheManager._lock", "TierManager._lock",
                      "HostKVStore._lock"),
        "release_lease": ("PrefixCacheManager._lock",),
        "release": ("PrefixCacheManager._lock", "TierManager._lock",
                    "HostKVStore._lock"),
        "invalidate_for_version": ("PrefixCacheManager._lock",
                                   "TierManager._lock",
                                   "HostKVStore._lock"),
    },
}

# Drift-check scope: the file that actually defines each class above.
# Fixture/test files re-declaring the class name are not held to the
# table (they exercise the analysis, not the real inventory).
LOCKING_METHODS_HOME = {
    "TierManager": "inference/v2/kv_tier/tier_manager.py",
    "HostKVStore": "inference/v2/kv_tier/host_store.py",
    "PrefixCacheManager": "inference/v2/prefix_cache/manager.py",
}

# lock-order: registered-class methods that can BLOCK (fence waits,
# worker joins) — calling one through a cross-ref while holding any
# lock is a blocking-under-lock violation even though the blocking call
# itself is one level down.
BLOCKING_METHODS = {
    "TierManager": {"wait_prefetch", "shutdown"},
    "ServingGateway": {"drain", "close"},
    "FleetRouter": {"drain", "shutdown"},
}

# Blocking-call heuristics for the in-method walk.
_BLOCKING_DOTTED = {"jax.device_get", "jax.block_until_ready",
                    "subprocess.run", "subprocess.call",
                    "subprocess.check_call", "subprocess.check_output",
                    "os.waitpid"}
_JOIN_RECEIVER_HINTS = ("thread", "worker", "relay", "pump", "agent")
_SLEEP_UNDER_LOCK_THRESHOLD_S = 0.01

# spec-consistency dtype-leak scope (fp32 Python constants materialized
# as arrays in bf16 arithmetic): kernel and model code only (plus the
# grouped-GEMM dispatch, which sits one level up from ops/pallas but
# builds the kernel's padded layouts in the activation dtype).
_DTYPE_DIRS = ("ops/pallas/", "models/", "ops/grouped_gemm")
_JNP_CTORS = {"jnp.array": 2, "jnp.asarray": 2, "jnp.ones": 2,
              "jnp.zeros": 2, "jnp.full": 3}  # value -> positional arity
#  with dtype

# wire-contract: the files whose hand-maintained agreement IS the
# cross-process protocol. Suffix-matched (like _HOT_PATHS) so fixture
# mirrors under a tmp root are held to the same contract in tests.
_WIRE_REPLICA_FILE = "serving/fleet/replica.py"
_WIRE_CLIENT_FILE = "serving/fleet/wire/client.py"
_WIRE_SERVER_FILE = "serving/fleet/wire/server.py"
_WIRE_ERRORS_FILE = "serving/fleet/wire/errors.py"

# Wire ops with no same-named abstract Replica method: ``cancel`` is
# handle-level (client side lives on _WireHandle, server side on the
# stream registry), so it is exempt from the method<->op parity check
# but still held to client<->server parity.
_WIRE_HANDLE_OPS = {"cancel"}

# Codec-send call names whose dict arguments must be literal-keyed
# wire-safe payloads (checked on the wire client/server files only).
_WIRE_SEND_FUNCS = {"write_frame", "send", "_send", "_safe_send"}

# replay-determinism scope: file suffix -> REPLAY_CRITICAL qualnames.
# Everything listed here feeds bit-identical replay — failover replay
# verification, disagg continuation verify, refresh canary compare,
# autotune trace replay — so any nondeterminism (unseeded RNG, wall
# clock flowing into token-visible state, unordered set iteration,
# salted hashes) silently breaks exactness fleet-wide. An entry may be
# a function, a ``Class.method``, a class name (every method is then
# critical), or ``"*"`` (the whole module). Rationale per entry lives
# in docs/LINTING.md.
REPLAY_CRITICAL = {
    "inference/v2/engine_v2.py": {
        "InferenceEngineV2.put",
        "InferenceEngineV2.decode_burst",
        "InferenceEngineV2.decode_burst_async",
        "InferenceEngineV2.verify_burst",
        "InferenceEngineV2.draw_seed",
        "AsyncBurstHandle.fetch",
    },
    "inference/v2/scheduler.py": {
        "DynamicSplitFuseScheduler._plan",
        "DynamicSplitFuseScheduler._try_burst",
        "DynamicSplitFuseScheduler._try_spec_burst",
        "DynamicSplitFuseScheduler._plan_async_k",
    },
    "inference/structured/prng.py": {"*"},
    "inference/structured/sampling.py": {"*"},
    "inference/v2/kv_tier/tier_manager.py": {
        "TierManager.export_chain",
        "TierManager.import_chain",
    },
    "serving/fleet/handoff.py": {"HandoffManager"},
    "serving/fleet/router.py": {
        "FleetRouter._serve",
        "FleetRouter._serve_disagg",
        "FleetRouter._attempt",
        "FleetRouter._backoff",
    },
    "autotuning/trace.py": {
        "synthesize_trace",
        "replay_lockstep",
        "replay_realtime",
    },
}

# Wall-clock reads that are nondeterministic across replays.
_REPLAY_WALL_CLOCK = {"time.time", "time.time_ns", "time.monotonic",
                      "time.monotonic_ns", "time.perf_counter",
                      "time.perf_counter_ns", "time.process_time",
                      "datetime.now", "datetime.datetime.now",
                      "datetime.utcnow", "datetime.datetime.utcnow"}
# Deadline/metrics idiom: a clock read assigned to a *-named local (or
# combined arithmetically / compared — elapsed math and deadline checks)
# never reaches token-visible state; anything else in a REPLAY_CRITICAL
# scope is flagged.
_CLOCK_IDIOM_NAMES = ("deadline", "timeout", "expire", "until", "retry",
                      "start", "t0", "now", "beat", "elapsed", "wall")
# Seeded RNG constructors: allowed in REPLAY_CRITICAL scope when given
# an explicit seed argument.
_SEEDED_RNG_CTORS = {"Random", "default_rng", "RandomState", "Generator"}


# ----------------------------------------------------------------- helpers
def _dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last(dotted):
    return dotted.rsplit(".", 1)[-1] if dotted else None


def _self_attr(node):
    """'attr' when node is ``self.attr`` (unwrapping subscripts:
    ``self.attr[k]`` → 'attr'), else None."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _has_float_literal(node):
    """True when node is/contains a non-bool float constant (the thing
    that silently materializes as fp32)."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, float)
    if isinstance(node, ast.UnaryOp):
        return _has_float_literal(node.operand)
    if isinstance(node, (ast.List, ast.Tuple)):
        return any(_has_float_literal(e) for e in node.elts)
    return False


def _parse_pragmas(source):
    """line -> set of disabled rule names ('all' disables everything).
    A pragma on its own line applies to the next line too."""
    pragmas = {}
    for lineno, text in enumerate(source.splitlines(), start=1):
        idx = text.find("# ds-lint:")
        if idx < 0:
            continue
        body = text[idx + len("# ds-lint:"):]
        body = body.split("--", 1)[0]  # strip the reason
        body = body.strip()
        if not body.startswith("disable="):
            continue
        rules = {r.strip() for r in body[len("disable="):].split(",") if r.strip()}
        pragmas.setdefault(lineno, set()).update(rules)
        if text[:idx].strip() == "":  # standalone pragma line
            pragmas.setdefault(lineno + 1, set()).update(rules)
    return pragmas


class BaselineError(ValueError):
    """Malformed or unsupported baseline.json (typed so the CLI can
    turn it into a clean exit-2 instead of a traceback)."""


def load_baseline(path):
    """tools/graft_lint/baseline.json → set of (rule, path, symbol)
    triples. Line numbers are deliberately not part of the key."""
    with open(path) as fd:
        try:
            data = json.load(fd)
        except json.JSONDecodeError as e:
            raise BaselineError(f"baseline {path} is not valid JSON: {e}")
    if not isinstance(data, dict):
        raise BaselineError(f"baseline {path} must be a JSON object, "
                            f"got {type(data).__name__}")
    if data.get("version") != 1:
        raise BaselineError(f"unsupported baseline version in {path}")
    entries = data.get("suppressions", ())
    if not isinstance(entries, list):
        raise BaselineError(f"baseline {path} 'suppressions' must be a list")
    out = set()
    for e in entries:
        if not isinstance(e, dict) or "rule" not in e or "path" not in e:
            raise BaselineError(f"baseline {path} entry {e!r} needs "
                                f"'rule' and 'path' keys")
        out.add((e["rule"], e["path"], e.get("symbol", "")))
    return out


# --------------------------------------------------------------- the pass
class FileLinter:

    def __init__(self, path, source, relpath=None):
        self.path = path
        # rule scoping matches on /-separated relative paths
        self.relpath = (relpath or path).replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.violations = []
        # surviving lock-acquisition edges (rank-clean, unpragma'd) for
        # the cross-file cycle pass run by lint_paths/lint_file
        self.lock_edges = []
        # per-file wire-contract facts (op tables, relay methods, error
        # classes) for the cross-file parity pass; filled by
        # check_wire_contract, merged by wire_contract_violations
        self.wire_info = None
        # parent / scope bookkeeping filled by _annotate
        self._parents = {}
        self._qualnames = {}
        self._traced = set()  # FunctionDef/Lambda nodes traced by jit
        self._annotate()

    # -- tree annotation ---------------------------------------------------
    def _annotate(self):
        defs_by_name = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs_by_name.setdefault(node.name, []).append(node)
        # dotted scope names
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                parts = [node.name]
                p = self._parents.get(node)
                while p is not None:
                    if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                        parts.append(p.name)
                    p = self._parents.get(p)
                self._qualnames[node] = ".".join(reversed(parts))

        # traced functions: decorated with a jit wrapper, or passed as
        # the first argument to one
        roots = set()
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    if _last(_dotted(target)) in _JIT_WRAPPERS:
                        roots.add(node)
            if isinstance(node, ast.Call) and \
                    _last(_dotted(node.func)) in _JIT_WRAPPERS and node.args:
                wrapped = node.args[0]
                if isinstance(wrapped, ast.Lambda):
                    roots.add(wrapped)
                elif isinstance(wrapped, ast.Name):
                    for d in defs_by_name.get(wrapped.id, ()):
                        roots.add(d)
        # everything defined inside a traced function traces with it
        for root in roots:
            for sub in ast.walk(root):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                    self._traced.add(sub)
        self._traced |= roots
        self._traced_roots = roots

    def _qualname(self, node):
        return self._qualnames.get(node, "<module>")

    def _enclosing_symbol(self, node):
        p = node
        while p is not None:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                return self._qualname(p)
            p = self._parents.get(p)
        return "<module>"

    def _emit(self, rule, node, message):
        self.violations.append(Violation(
            rule=rule, path=self.relpath, line=node.lineno,
            col=getattr(node, "col_offset", 0),
            symbol=self._enclosing_symbol(node), message=message))

    # -- rule 1: jit-purity ------------------------------------------------
    def check_jit_purity(self):
        for fn in self._traced:
            # Only the ROOT traced function's params are definitely
            # tracers. Nested-def params are often static metadata bound
            # through jax.tree.map (partition dims, config), so the
            # branch check stays root-only; side-effect checks apply to
            # the whole traced subtree.
            params = set()
            if fn in self._traced_roots:
                args = fn.args
                for a in (args.posonlyargs + args.args + args.kwonlyargs
                          + ([args.vararg] if args.vararg else [])
                          + ([args.kwarg] if args.kwarg else [])):
                    params.add(a.arg)
                params.discard("self")
            for node in ast.walk(fn):
                if node is fn:
                    continue
                # nested defs/lambdas are traced too and get their own
                # iteration — only check nodes fn directly owns
                if self._owner_fn(node) is not fn:
                    continue
                self._check_purity_node(fn, node, params)

    def _owner_fn(self, node):
        p = self._parents.get(node)
        while p is not None:
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return p
            p = self._parents.get(p)
        return None

    def _check_purity_node(self, fn, node, params):
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            root = dotted.split(".", 1)[0] if dotted else None
            if root in ("time", "random") or (
                    dotted and dotted.startswith(("np.random.",
                                                  "numpy.random."))):
                self._emit(JIT_PURITY, node,
                           f"call to {dotted}() inside a traced function "
                           f"runs at TRACE time only (or reorders under "
                           f"compilation) — hoist it out of the jitted "
                           f"region")
            elif dotted == "print":
                self._emit(JIT_PURITY, node,
                           "print() inside a traced function fires at "
                           "trace time only; use jax.debug.print")
            elif dotted == "os.getenv":
                self._emit(JIT_PURITY, node,
                           "os.getenv inside a traced function is a "
                           "trace-time constant; read it before tracing")
        if isinstance(node, ast.Attribute) and \
                _dotted(node) == "os.environ":
            self._emit(JIT_PURITY, node,
                       "os.environ inside a traced function is a "
                       "trace-time constant; read it before tracing")
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                    if _self_attr(el) is not None:
                        self._emit(JIT_PURITY, node,
                                   f"mutation of self.{_self_attr(el)} "
                                   f"inside a traced function happens at "
                                   f"trace time, not per call")
        if isinstance(node, (ast.If, ast.While)):
            if self._branches_on_param(node.test, params):
                kind = "if" if isinstance(node, ast.If) else "while"
                self._emit(JIT_PURITY, node,
                           f"Python `{kind}` on a traced argument forces "
                           f"concretization (TracerBoolConversionError at "
                           f"runtime); use lax.cond/jnp.where")

    def _branches_on_param(self, test, params):
        """Bare-name truthiness / value comparison on a traced parameter.
        Identity and containment checks (``is None``, ``in``) are static
        pytree-structure tests and stay legal."""
        if isinstance(test, ast.Name):
            return test.id in params
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._branches_on_param(test.operand, params)
        if isinstance(test, ast.BoolOp):
            return any(self._branches_on_param(v, params) for v in test.values)
        if isinstance(test, ast.Compare):
            if any(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in test.ops):
                return False
            return any(isinstance(e, ast.Name) and e.id in params
                       for e in [test.left] + test.comparators)
        return False

    # -- rule 2: host-sync -------------------------------------------------
    def check_host_sync(self):
        hot = None
        for suffix, names in _HOT_PATHS.items():
            if self.relpath.endswith(suffix):
                hot = names
                break
        if hot is None:
            return
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if self._qualname(node) not in hot:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func)
                if isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in _SYNC_ATTRS:
                    self._emit(HOST_SYNC, sub,
                               f".{sub.func.attr}() in a serving hot path "
                               f"blocks on the device — keep this path "
                               f"async")
                elif dotted in _SYNC_DOTTED:
                    self._emit(HOST_SYNC, sub,
                               f"{dotted}() in a serving hot path copies "
                               f"device data to host (implicit sync)")
                elif dotted in _SYNC_BUILTINS and sub.args and isinstance(
                        sub.args[0], (ast.Name, ast.Attribute, ast.Subscript)):
                    self._emit(HOST_SYNC, sub,
                               f"{dotted}() on an array in a serving hot "
                               f"path forces a device sync")

    # -- rule 3: thread-shared-state --------------------------------------
    def check_thread_shared(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = THREAD_SHARED_REGISTRY.get(node.name)
            if not attrs:
                continue
            for method in node.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name == "__init__":
                    continue  # not yet published to other threads
                self._check_method_writes(method, attrs)

    def _check_method_writes(self, method, attrs):
        for node in ast.walk(method):
            written = None
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                        a = _self_attr(el)
                        if a in attrs:
                            written = a
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                a = _self_attr(node.func.value)
                if a in attrs:
                    written = a
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    a = _self_attr(t)
                    if a in attrs:
                        written = a
            if written is not None and not self._under_lock(node):
                self._emit(THREAD_SHARED, node,
                           f"write to shared self.{written} outside a "
                           f"`with self.<lock>:` block "
                           f"(class is touched by multiple threads)")

    def _under_lock(self, node):
        p = self._parents.get(node)
        while p is not None:
            if isinstance(p, ast.With):
                for item in p.items:
                    ctx = item.context_expr
                    if isinstance(ctx, ast.Call):
                        ctx = ctx.func  # e.g. self._lock.acquire_timeout()
                    d = _dotted(ctx)
                    if d and d.startswith("self.") and "lock" in d.lower():
                        return True
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break  # don't credit an outer function's lock
            p = self._parents.get(p)
        return False

    # -- rule 4: spec-consistency ------------------------------------------
    def check_spec_consistency(self):
        spec_ctors = {"PartitionSpec"}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "PartitionSpec" and alias.asname:
                        spec_ctors.add(alias.asname)
        allowed = set(MESH_AXES)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _last(_dotted(node.func))
            if name in spec_ctors:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    for el in (arg.elts if isinstance(arg, (ast.Tuple,
                                                            ast.List))
                               else [arg]):
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str) and \
                                el.value not in allowed:
                            self._emit(SPEC_CONSISTENCY, el,
                                       f"PartitionSpec axis {el.value!r} is "
                                       f"not a declared mesh axis "
                                       f"{MESH_AXES}")
            if any(self.relpath.rpartition("deepspeed_tpu/")[2]
                   .startswith(d) for d in _DTYPE_DIRS):
                dotted = _dotted(node.func)
                arity = _JNP_CTORS.get(dotted)
                if arity is not None and len(node.args) < arity and \
                        not any(kw.arg == "dtype" for kw in node.keywords):
                    value_args = node.args[-1:] if dotted == "jnp.full" \
                        else node.args[:1]
                    if any(_has_float_literal(a) for a in value_args):
                        self._emit(SPEC_CONSISTENCY, node,
                                   f"{dotted}() on a float literal without "
                                   f"dtype= materializes fp32 and promotes "
                                   f"bf16 arithmetic — pass dtype explicitly")

    # -- rule 5: env-registry ----------------------------------------------
    def check_env_registry(self):
        if self.relpath.endswith("utils/env_registry.py"):
            return
        for node in ast.walk(self.tree):
            key = None
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted in ("os.environ.get", "os.getenv") and node.args:
                    key = node.args[0]
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and \
                    _dotted(node.value) == "os.environ":
                key = node.slice
            elif isinstance(node, ast.Compare) and \
                    len(node.ops) == 1 and \
                    isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                    _dotted(node.comparators[0]) == "os.environ":
                key = node.left
            if isinstance(key, ast.Constant) and \
                    isinstance(key.value, str) and \
                    key.value.startswith("DS_"):
                self._emit(ENV_REGISTRY, node,
                           f"read of {key.value} bypasses "
                           f"deepspeed_tpu/utils/env_registry.py — use "
                           f"env_bool/env_int/env_str/env_raw")

    # -- rule 6: lock-order ------------------------------------------------
    def check_lock_order(self):
        """Per registered class, walk each method with a held-lock stack
        and (a) emit acquisition edges checked against LOCK_ORDER (rank
        inversions flagged here; surviving edges collected on
        ``self.lock_edges`` for cross-file cycle detection), (b) flag
        blocking calls reached while any lock is held, (c) flag
        re-acquisition of a non-reentrant lock, (d) keep the declared
        LOCKING_METHODS table honest on each class's home file."""
        for cls in ast.walk(self.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            if cls.name not in THREAD_SHARED_REGISTRY:
                continue
            locks, cond_target = self._discover_locks(cls)
            methods = [m for m in cls.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            summaries = {m.name: self._method_lock_summary(cls.name, m, locks,
                                                           cond_target)
                         for m in methods}
            self._check_locking_methods_drift(cls, methods, summaries)
            for method in methods:
                if method.name == "__init__":
                    continue  # not yet published; lock wiring lives here
                ctx = {"cls": cls.name, "locks": locks,
                       "cond_target": cond_target, "aliases": {},
                       "held": [], "summaries": summaries}
                if method.name.endswith("_locked") and "_lock" in locks:
                    # caller-holds-the-lock convention: analyze the body
                    # as if the class's primary lock is already held
                    ctx["held"].append({"key": f"{cls.name}._lock",
                                        "kind": locks["_lock"],
                                        "seed": True})
                self._walk_lock_stmts(method.body, ctx)

    # lock discovery -------------------------------------------------------
    def _discover_locks(self, cls):
        """``__init__`` assignments → {attr: 'lock'|'rlock'|'condition'}
        plus {condition attr: underlying lock attr} (a ``Condition(self.X)``
        aliases X; a bare ``Condition()`` owns its lock — reentrant).
        ``tracked_lock(...)`` wrappers (the DS_SANITIZE runtime twin) are
        unwrapped to the real constructor."""
        locks, cond_target = {}, {}
        init = next((m for m in cls.body if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is None:
            return locks, cond_target
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            attr = _self_attr(node.targets[0])
            if attr is None:
                continue
            value = node.value
            if isinstance(value, ast.Call) and \
                    _last(_dotted(value.func)) == "tracked_lock" and value.args:
                value = value.args[0]
            if not isinstance(value, ast.Call):
                continue
            ctor = _last(_dotted(value.func))
            if ctor == "Lock":
                locks[attr] = "lock"
            elif ctor == "RLock":
                locks[attr] = "rlock"
            elif ctor == "Condition":
                locks[attr] = "condition"
                tgt = _self_attr(value.args[0]) if value.args else None
                cond_target[attr] = tgt if tgt else attr
        return locks, cond_target

    def _resolve_lock(self, expr, ctx):
        """→ (lock key 'Class.attr', kind, local attr) or None. Handles
        ``self.X`` (declared locks and *lock*-named fallbacks),
        ``self.ref._lock`` through CROSS_REFS, local object/lock
        aliases, and ``self.X.acquire*()`` call forms."""
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Attribute) and f.attr.startswith("acquire"):
                expr = f.value
            else:
                return None
        d = _dotted(expr)
        if d is None:
            return None
        parts = d.split(".")
        locks, cond_target = ctx["locks"], ctx["cond_target"]
        if parts[0] == "self" and len(parts) == 2:
            attr = parts[1]
            if attr in locks:
                target = cond_target.get(attr, attr)
                kind = locks.get(target, locks[attr])
                return (f"{ctx['cls']}.{target}", kind, attr)
            if "lock" in attr.lower():
                return (f"{ctx['cls']}.{attr}", "unknown", attr)
            return None
        if parts[0] == "self" and len(parts) == 3:
            peer = CROSS_REFS.get(ctx["cls"], {}).get(parts[1])
            if peer and "lock" in parts[2].lower():
                return (f"{peer}.{parts[2]}", "unknown", parts[2])
            return None
        if len(parts) == 2 and parts[0] in ctx["aliases"]:
            akind, val = ctx["aliases"][parts[0]]
            if akind == "obj" and "lock" in parts[1].lower():
                return (f"{val}.{parts[1]}", "unknown", parts[1])
            return None
        if len(parts) == 1 and parts[0] in ctx["aliases"]:
            akind, val = ctx["aliases"][parts[0]]
            if akind == "lock":
                return val
        return None

    def _resolve_peer(self, recv, ctx):
        """Receiver expression → peer registered class name, via
        CROSS_REFS (``self.tier``) or a tracked local alias."""
        d = _dotted(recv)
        if d is None:
            return None
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2:
            return CROSS_REFS.get(ctx["cls"], {}).get(parts[1])
        if len(parts) == 1 and parts[0] in ctx["aliases"]:
            akind, val = ctx["aliases"][parts[0]]
            if akind == "obj":
                return val
        return None

    def _method_lock_summary(self, cls_name, method, locks, cond_target):
        """Locks this method DIRECTLY acquires (``with``/``.acquire()``
        on self locks) — the one-level summary intra-class calls and the
        LOCKING_METHODS drift check consume."""
        ctx = {"cls": cls_name, "locks": locks, "cond_target": cond_target,
               "aliases": {}}
        out = set()
        for node in ast.walk(method):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    res = self._resolve_lock(item.context_expr, ctx)
                    if res:
                        out.add(res[:2])
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "acquire":
                res = self._resolve_lock(node, ctx)
                if res:
                    out.add(res[:2])
        return out

    def _check_locking_methods_drift(self, cls, methods, summaries):
        declared = LOCKING_METHODS.get(cls.name)
        home = LOCKING_METHODS_HOME.get(cls.name)
        if not declared or not home or not self.relpath.endswith(home):
            return
        by_name = {m.name: m for m in methods}
        prefix = cls.name + "."
        for mname, keys in sorted(declared.items()):
            if mname not in by_name:
                self._emit(LOCK_ORDER_RULE, cls,
                           f"LOCKING_METHODS declares {cls.name}.{mname} "
                           f"which no longer exists — update the table in "
                           f"tools/graft_lint/linter.py")
                continue
            direct_self = {key for key, _kind in summaries.get(mname, ())
                           if key.startswith(prefix)}
            missing = direct_self - set(keys)
            if missing:
                self._emit(LOCK_ORDER_RULE, by_name[mname],
                           f"{cls.name}.{mname} acquires "
                           f"{sorted(missing)} not declared in "
                           f"LOCKING_METHODS — update the table")
        for mname, m in sorted(by_name.items()):
            if mname.startswith("_") or mname in declared:
                continue
            self_locks = {key for key, _kind in summaries.get(mname, ())
                          if key.startswith(prefix)}
            if self_locks:
                self._emit(LOCK_ORDER_RULE, m,
                           f"public locking method {cls.name}.{mname} "
                           f"(acquires {sorted(self_locks)}) is missing "
                           f"from LOCKING_METHODS — peers calling it "
                           f"under a lock would be invisible to the "
                           f"deadlock analysis")

    # held-stack statement walk -------------------------------------------
    def _walk_lock_stmts(self, stmts, ctx):
        for stmt in stmts:
            self._walk_lock_stmt(stmt, ctx)

    def _walk_lock_stmt(self, stmt, ctx):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs run later, not under these locks
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                res = self._resolve_lock(item.context_expr, ctx)
                if res is not None:
                    self._note_acquisition(res, item.context_expr, ctx)
                    pushed += 1
                else:
                    self._scan_exprs(item.context_expr, ctx)
            self._walk_lock_stmts(stmt.body, ctx)
            for _ in range(pushed):
                ctx["held"].pop()
            return
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            self._track_alias(stmt, ctx)
        # scan this statement's own expressions (not nested blocks)
        for field, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                self._scan_exprs(value, ctx)
            elif isinstance(value, list):
                for el in value:
                    if isinstance(el, ast.expr):
                        self._scan_exprs(el, ctx)
        # then recurse into nested statement blocks
        for field in ("body", "orelse", "finalbody"):
            block = getattr(stmt, field, None)
            if block:
                self._walk_lock_stmts(block, ctx)
        for handler in getattr(stmt, "handlers", ()):
            self._walk_lock_stmts(handler.body, ctx)

    def _track_alias(self, stmt, ctx):
        name = stmt.targets[0].id
        ctx["aliases"].pop(name, None)
        attr = _self_attr(stmt.value)
        if attr is None:
            return
        peer = CROSS_REFS.get(ctx["cls"], {}).get(attr)
        if peer is not None:
            ctx["aliases"][name] = ("obj", peer)
        elif attr in ctx["locks"] or "lock" in attr.lower():
            res = self._resolve_lock(stmt.value, ctx)
            if res is not None:
                ctx["aliases"][name] = ("lock", res)

    def _note_acquisition(self, res, node, ctx, via_call=False):
        key, kind, _attr = res
        held = ctx["held"]
        if any(e["key"] == key for e in held):
            if kind == "lock":
                self._emit(LOCK_ORDER_RULE, node,
                           f"re-acquisition of non-reentrant {key} while "
                           f"already held — this deadlocks (use an RLock "
                           f"or restructure)")
            held.append({"key": key, "kind": kind, "via_call": via_call})
            return
        for e in held:
            self._note_edge(e["key"], key, node, ctx)
        held.append({"key": key, "kind": kind, "via_call": via_call})

    def _note_edge(self, src, dst, node, ctx):
        if src == dst:
            return
        rs, rd = LOCK_ORDER.get(src), LOCK_ORDER.get(dst)
        if rs is not None and rd is not None and rs > rd:
            self._emit(LOCK_ORDER_RULE, node,
                       f"acquires {dst} while holding {src} — inverts the "
                       f"canonical lock order ({dst} rank {rd} is taken "
                       f"BEFORE {src} rank {rs}; see LOCK_ORDER in "
                       f"tools/graft_lint/linter.py)")
            return  # already reported; keep it out of the cycle graph
        self.lock_edges.append({
            "src": src, "dst": dst, "path": self.relpath,
            "line": node.lineno, "col": getattr(node, "col_offset", 0),
            "symbol": self._enclosing_symbol(node)})

    def _scan_exprs(self, expr, ctx):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._scan_lock_call(node, ctx)

    def _scan_lock_call(self, call, ctx):
        held = ctx["held"]
        dotted = _dotted(call.func)
        if not isinstance(call.func, ast.Attribute):
            return
        meth = call.func.attr
        recv = call.func.value
        # explicit acquire()/release() pairs
        if meth == "acquire":
            res = self._resolve_lock(call, ctx)
            if res is not None:
                self._note_acquisition(res, call, ctx, via_call=True)
                return
        elif meth == "release":
            res = self._resolve_lock(
                ast.Call(func=ast.Attribute(value=recv, attr="acquire",
                                            ctx=ast.Load()),
                         args=[], keywords=[]), ctx)
            if res is not None:
                for i in range(len(held) - 1, -1, -1):
                    if held[i]["key"] == res[0] and held[i].get("via_call"):
                        del held[i]
                        break
                return
        if not held:
            return
        held_keys = [e["key"] for e in held]
        held_desc = ", ".join(dict.fromkeys(held_keys))
        # blocking-call heuristics ------------------------------------
        recv_d = (_dotted(recv) or "").lower()
        if meth == "join" and any(h in recv_d for h in _JOIN_RECEIVER_HINTS):
            self._emit(LOCK_ORDER_RULE, call,
                       f"Thread.join on {_dotted(recv)} while holding "
                       f"{held_desc} — joining a thread that may need the "
                       f"lock is a deadlock; join outside the lock")
            return
        if meth == "get" and not call.args and not call.keywords and \
                recv_d != "self":
            self._emit(LOCK_ORDER_RULE, call,
                       f"blocking .get() (no timeout) on {_dotted(recv)} "
                       f"while holding {held_desc}")
            return
        if meth == "wait" and not self._wait_is_timed(call):
            if not self._wait_is_condition_of_held(recv, ctx):
                self._emit(LOCK_ORDER_RULE, call,
                           f"untimed .wait() on {_dotted(recv)} while "
                           f"holding {held_desc} — only a Condition of "
                           f"the (sole) held lock may wait under it")
            return
        if meth == "communicate" and \
                not any(kw.arg == "timeout" for kw in call.keywords):
            self._emit(LOCK_ORDER_RULE, call,
                       f"subprocess communicate() while holding "
                       f"{held_desc}")
            return
        if meth == "block_until_ready" or dotted in _BLOCKING_DOTTED:
            self._emit(LOCK_ORDER_RULE, call,
                       f"device sync / process wait ({dotted or meth}) "
                       f"while holding {held_desc}")
            return
        if dotted == "time.sleep":
            arg = call.args[0] if call.args else None
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, (int, float))
                    and arg.value <= _SLEEP_UNDER_LOCK_THRESHOLD_S):
                self._emit(LOCK_ORDER_RULE, call,
                           f"time.sleep under {held_desc} stalls every "
                           f"thread contending for the lock")
            return
        # call resolution, one level deep -----------------------------
        if isinstance(recv, ast.Name) and recv.id == "self":
            summary = ctx["summaries"].get(meth)
            if summary:
                for key, kind in sorted(summary):
                    if key in held_keys:
                        if kind == "lock":
                            self._emit(LOCK_ORDER_RULE, call,
                                       f"call to self.{meth}() re-acquires "
                                       f"non-reentrant {key} already held "
                                       f"by this method")
                        continue
                    self._note_edge(held_keys[-1], key, call, ctx)
            return
        peer = self._resolve_peer(recv, ctx)
        if peer is None:
            return
        if meth in BLOCKING_METHODS.get(peer, ()):
            self._emit(LOCK_ORDER_RULE, call,
                       f"call to blocking {peer}.{meth}() while holding "
                       f"{held_desc}")
            return
        for key in LOCKING_METHODS.get(peer, {}).get(meth, ()):
            if key in held_keys:
                continue
            self._note_edge(held_keys[-1], key, call, ctx)

    @staticmethod
    def _wait_is_timed(call):
        if call.args:
            a = call.args[0]
            return not (isinstance(a, ast.Constant) and a.value is None)
        for kw in call.keywords:
            if kw.arg == "timeout":
                return not (isinstance(kw.value, ast.Constant)
                            and kw.value.value is None)
        return False

    def _wait_is_condition_of_held(self, recv, ctx):
        """Untimed Condition.wait is legal exactly when the condition's
        underlying lock is the ONLY lock held: the wait releases it, so
        nothing stays pinned while sleeping."""
        attr = _self_attr(recv)
        if attr is None or ctx["locks"].get(attr) != "condition":
            return False
        target = ctx["cond_target"].get(attr, attr)
        target_key = f"{ctx['cls']}.{target}"
        return {e["key"] for e in ctx["held"]} == {target_key}

    # -- rule 7: wire-contract ---------------------------------------------
    def check_wire_contract(self):
        """Collect this file's wire-contract facts (Replica interface,
        client relays + ops sent, server op table, error-registry
        imports, error-class shapes) onto ``self.wire_info`` for the
        cross-file parity pass, and run the per-file payload check:
        dict literals handed to the codec must be literal-keyed."""
        info = {"relpath": self.relpath,
                "pragmas": _parse_pragmas(self.source),
                "classes": [], "replica_methods": {}, "client_methods": {},
                "client_ops": {}, "server_ops": {}, "registry_imports": {},
                "replica_line": 1, "client_line": 1, "server_line": 1,
                "registry_line": 1}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._collect_wire_class(node, info)
            elif isinstance(node, ast.FunctionDef) and \
                    node.name == "_error_registry" and \
                    self.relpath.endswith(_WIRE_ERRORS_FILE):
                info["registry_line"] = node.lineno
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Import):
                        for alias in sub.names:
                            info["registry_imports"].setdefault(
                                alias.name, sub.lineno)
                    elif isinstance(sub, ast.ImportFrom) and sub.module:
                        info["registry_imports"].setdefault(
                            sub.module, sub.lineno)
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute):
                    op = None
                    if f.attr == "_call" and node.args:
                        op = node.args[0]
                    elif f.attr == "_send" and len(node.args) >= 2:
                        op = node.args[1]
                    if isinstance(op, ast.Constant) and \
                            isinstance(op.value, str):
                        info["client_ops"].setdefault(op.value, node.lineno)
            if isinstance(node, ast.Compare) and \
                    isinstance(node.left, ast.Name) and \
                    node.left.id == "op" and len(node.ops) == 1 and \
                    isinstance(node.ops[0], ast.Eq) and \
                    isinstance(node.comparators[0], ast.Constant) and \
                    isinstance(node.comparators[0].value, str):
                info["server_ops"].setdefault(node.comparators[0].value,
                                              node.lineno)
        if self.relpath.endswith((_WIRE_CLIENT_FILE, _WIRE_SERVER_FILE)):
            self._check_wire_payloads()
        self.wire_info = info

    def _collect_wire_class(self, node, info):
        bases = [b for b in (_last(_dotted(b)) for b in node.bases) if b]
        init = next((m for m in node.body
                     if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        ctor_ok = True
        if init is not None:
            a = init.args
            required = len(a.posonlyargs) + len(a.args) - len(a.defaults)
            accepts_msg = (len(a.posonlyargs) + len(a.args) >= 2) or \
                a.vararg is not None
            kw_required = any(d is None for d in a.kw_defaults)
            ctor_ok = accepts_msg and required <= 2 and not kw_required
        declared = set()
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        declared.add(t.id)
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                declared.add(stmt.target.id)
        info["classes"].append({
            "name": node.name, "bases": bases, "line": node.lineno,
            "has_reason": "reason" in declared,
            "has_retry": "retry_elsewhere" in declared,
            "ctor_ok": ctor_ok})
        methods = {m.name: m.lineno for m in node.body
                   if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and not m.name.startswith("_")}
        if node.name == "Replica" and \
                self.relpath.endswith(_WIRE_REPLICA_FILE):
            info["replica_methods"] = methods
            info["replica_line"] = node.lineno
        elif node.name == "WireReplica" and \
                self.relpath.endswith(_WIRE_CLIENT_FILE):
            info["client_methods"] = methods
            info["client_line"] = node.lineno
        elif node.name == "ReplicaServer" and \
                self.relpath.endswith(_WIRE_SERVER_FILE):
            info["server_line"] = node.lineno

    def _check_wire_payloads(self):
        """Dict payloads handed to the codec (`write_frame`, `.send`,
        `._send`, `._safe_send`) must have literal string keys and no
        set values — non-literal keys defeat static parity checking and
        sets do not survive either wire format."""
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            local_dicts = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        isinstance(node.value, ast.Dict):
                    local_dicts[node.targets[0].id] = node.value
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _last(_dotted(node.func))
                if name not in _WIRE_SEND_FUNCS:
                    continue
                for arg in node.args:
                    d = arg if isinstance(arg, ast.Dict) else \
                        (local_dicts.get(arg.id)
                         if isinstance(arg, ast.Name) else None)
                    if d is None:
                        continue
                    for k in d.keys:
                        if k is None:
                            self._emit(WIRE_CONTRACT, node,
                                       "codec payload built with a **-"
                                       "expansion — wire payload dicts "
                                       "must be literal-keyed so the "
                                       "contract is statically checkable")
                        elif not (isinstance(k, ast.Constant)
                                  and isinstance(k.value, str)):
                            self._emit(WIRE_CONTRACT, k,
                                       "non-literal / non-string key in a "
                                       "codec payload dict — wire envelope "
                                       "keys must be string literals "
                                       "(msgpack/JSON both require it and "
                                       "static parity checks depend on it)")
                    for v in d.values:
                        for sub in ast.walk(v):
                            if isinstance(sub, (ast.Set, ast.SetComp)):
                                self._emit(WIRE_CONTRACT, sub,
                                           "set literal inside a codec "
                                           "payload — sets survive neither "
                                           "msgpack nor JSON; use a sorted "
                                           "list")

    # -- rule 8: replay-determinism ----------------------------------------
    def check_replay_determinism(self):
        entries = None
        for suffix, names in REPLAY_CRITICAL.items():
            if self.relpath.endswith(suffix):
                entries = names
                break
        if entries is None:
            return
        whole = "*" in entries

        def critical(fn):
            if whole:
                return True
            qn = self._qualname(fn)
            return any(qn == e or qn.startswith(e + ".") for e in entries)

        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not critical(fn):
                continue
            owner = self._owner_fn(fn)
            if owner is not None and critical(owner):
                continue  # nested def: walked with its owner
            self._check_replay_fn(fn)

    def _check_replay_fn(self, fn):
        set_names = self._settish_locals(fn)
        set_attrs = self._settish_class_attrs(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                self._check_replay_call(node, set_names, set_attrs)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if self._is_settish(node.iter, set_names, set_attrs):
                    self._emit(REPLAY_DETERMINISM, node,
                               "iteration over an unordered set in a "
                               "REPLAY_CRITICAL scope — set order varies "
                               "across processes and feeds packing/replay "
                               "order; wrap in sorted(...)")
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    if self._is_settish(gen.iter, set_names, set_attrs):
                        self._emit(REPLAY_DETERMINISM, node,
                                   "comprehension over an unordered set in "
                                   "a REPLAY_CRITICAL scope — wrap the "
                                   "iterable in sorted(...)")

    def _check_replay_call(self, node, set_names, set_attrs):
        dotted = _dotted(node.func)
        name = _last(dotted)
        if dotted is not None:
            if dotted.startswith("random."):
                if not (name in _SEEDED_RNG_CTORS and node.args):
                    self._emit(REPLAY_DETERMINISM, node,
                               f"{dotted}() in a REPLAY_CRITICAL scope "
                               f"draws from process-local entropy — seed "
                               f"explicitly (random.Random(derive_seed(...))"
                               f") or thread the counter PRNG through")
                return
            if dotted.startswith(("np.random.", "numpy.random.")):
                if not (name in _SEEDED_RNG_CTORS and node.args):
                    self._emit(REPLAY_DETERMINISM, node,
                               f"module-level {dotted}() in a "
                               f"REPLAY_CRITICAL scope is unseeded global "
                               f"state — use a seeded np.random.default_rng"
                               f"(seed) / the counter PRNG")
                return
            if dotted == "os.urandom" or dotted.startswith("secrets.") or \
                    name in ("uuid1", "uuid4"):
                self._emit(REPLAY_DETERMINISM, node,
                           f"{dotted or name}() is OS entropy — a replay "
                           f"can never reproduce it; derive identity/seeds "
                           f"from (DS_SEED, request uid, position)")
                return
            if dotted in _REPLAY_WALL_CLOCK:
                if not self._clock_idiom_exempt(node):
                    self._emit(REPLAY_DETERMINISM, node,
                               f"{dotted}() outside a deadline/metrics "
                               f"idiom in a REPLAY_CRITICAL scope — wall "
                               f"clock flowing into token-visible state "
                               f"breaks bit-identical replay")
                return
        if isinstance(node.func, ast.Name) and node.func.id in ("id", "hash"):
            which = "id() is a process-local address" if \
                node.func.id == "id" else \
                "hash() is PYTHONHASHSEED-salted for str/bytes"
            self._emit(REPLAY_DETERMINISM, node,
                       f"{which} — keys/seeds derived from it differ "
                       f"across processes and replays; use derive_seed() "
                       f"or an explicit stable key")
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "pop" and not node.args and \
                self._is_settish(node.func.value, set_names, set_attrs):
            self._emit(REPLAY_DETERMINISM, node,
                       "set.pop() removes an arbitrary element — "
                       "nondeterministic in a REPLAY_CRITICAL scope; pop "
                       "from a sorted/ordered structure")
            return
        if isinstance(node.func, ast.Name) and \
                node.func.id in ("list", "tuple", "enumerate", "iter") and \
                node.args and \
                self._is_settish(node.args[0], set_names, set_attrs):
            self._emit(REPLAY_DETERMINISM, node,
                       f"{node.func.id}() over an unordered set in a "
                       f"REPLAY_CRITICAL scope — materialized order varies "
                       f"across processes; use sorted(...)")

    def _clock_idiom_exempt(self, node):
        """Deadline/metrics idioms: the clock read participates in
        arithmetic/comparison (elapsed math, deadline checks) or is
        assigned to a deadline/metrics-named local."""
        p = self._parents.get(node)
        while p is not None and not isinstance(p, ast.stmt):
            if isinstance(p, (ast.BinOp, ast.Compare)):
                return True
            p = self._parents.get(p)
        if isinstance(p, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = p.targets if isinstance(p, ast.Assign) else [p.target]
            for t in targets:
                n = t.id if isinstance(t, ast.Name) else _self_attr(t)
                if n and any(h in n.lower() for h in _CLOCK_IDIOM_NAMES):
                    return True
        return False

    def _settish_locals(self, fn):
        out = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                v = node.value
                if isinstance(v, (ast.Set, ast.SetComp)) or (
                        isinstance(v, ast.Call)
                        and _last(_dotted(v.func)) in ("set", "frozenset")):
                    out.add(node.targets[0].id)
        return out

    def _settish_class_attrs(self, fn):
        """self-attributes assigned a set in the enclosing class's
        ``__init__`` — iterating them in a critical method is flagged."""
        cls = self._parents.get(fn)
        while cls is not None and not isinstance(cls, ast.ClassDef):
            cls = self._parents.get(cls)
        if cls is None:
            return set()
        init = next((m for m in cls.body if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is None:
            return set()
        out = set()
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                attr = _self_attr(node.targets[0])
                v = node.value
                if attr and (isinstance(v, (ast.Set, ast.SetComp)) or (
                        isinstance(v, ast.Call)
                        and _last(_dotted(v.func)) in ("set", "frozenset"))):
                    out.add(attr)
        return out

    def _is_settish(self, expr, set_names, set_attrs):
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return True
        if isinstance(expr, ast.Call) and \
                _last(_dotted(expr.func)) in ("set", "frozenset"):
            return True
        if isinstance(expr, ast.Name):
            return expr.id in set_names
        attr = _self_attr(expr)
        if attr is not None:
            return attr in set_attrs
        if isinstance(expr, ast.BinOp) and \
                isinstance(expr.op, (ast.BitOr, ast.BitAnd, ast.Sub,
                                     ast.BitXor)):
            return self._is_settish(expr.left, set_names, set_attrs) or \
                self._is_settish(expr.right, set_names, set_attrs)
        return False

    # -- driver ------------------------------------------------------------
    def run(self, only=None):
        checks = {
            JIT_PURITY: self.check_jit_purity,
            HOST_SYNC: self.check_host_sync,
            THREAD_SHARED: self.check_thread_shared,
            SPEC_CONSISTENCY: self.check_spec_consistency,
            ENV_REGISTRY: self.check_env_registry,
            LOCK_ORDER_RULE: self.check_lock_order,
            WIRE_CONTRACT: self.check_wire_contract,
            REPLAY_DETERMINISM: self.check_replay_determinism,
        }
        for rule, check in checks.items():
            if only is None or rule in only:
                check()
        pragmas = _parse_pragmas(self.source)
        kept = []
        for v in self.violations:
            disabled = pragmas.get(v.line, ())
            if v.rule in disabled or "all" in disabled:
                continue
            kept.append(v)
        kept.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
        # pragma'd edges leave the cycle graph too — a suppressed
        # acquisition site must not resurrect as a cycle report
        self.lock_edges = [
            e for e in self.lock_edges
            if LOCK_ORDER_RULE not in pragmas.get(e["line"], ())
            and "all" not in pragmas.get(e["line"], ())]
        return kept


def lock_cycle_violations(edges):
    """Cycle detection over merged acquisition edges. ``edges`` is a list
    of {src, dst, path, line, col, symbol} dicts; a DFS back-edge means
    two lock keys can be taken in both orders somewhere in the repo —
    each distinct cycle (deduped by its node set) is reported once,
    anchored at the back-edge acquisition site."""
    graph = {}
    sites = {}
    for e in edges:
        graph.setdefault(e["src"], set()).add(e["dst"])
        graph.setdefault(e["dst"], set())
        sites.setdefault((e["src"], e["dst"]), e)
    violations = []
    seen_cycles = set()
    color = {}  # node -> 1 (on stack) | 2 (done)
    stack = []

    def dfs(node):
        color[node] = 1
        stack.append(node)
        for nxt in sorted(graph[node]):
            if color.get(nxt) == 1:
                cycle = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cycle)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    site = sites[(node, nxt)]
                    violations.append(Violation(
                        rule=LOCK_ORDER_RULE, path=site["path"],
                        line=site["line"], col=site["col"],
                        symbol=site["symbol"],
                        message=("lock-acquisition cycle "
                                 + " -> ".join(cycle)
                                 + " — two code paths take these locks "
                                   "in opposite orders; assign ranks in "
                                   "LOCK_ORDER and fix the inversion")))
            elif color.get(nxt) != 2:
                dfs(nxt)
        stack.pop()
        color[node] = 2

    for node in sorted(graph):
        if node not in color:
            dfs(node)
    return violations


def wire_contract_violations(infos):
    """Cross-file wire-contract parity over the merged per-file facts
    (``FileLinter.wire_info``). Each agreement is only checked when
    both sides were actually linted, so single-file invocations never
    report a "missing" counterpart they simply did not see:

    - every abstract ``Replica`` method needs a ``WireReplica`` relay,
      a client op send, and a ``ReplicaServer`` op-table entry;
    - every op the client sends must be dispatched by the server, and
      every server op must be reachable from a relay (else dead);
    - every module defining a ``ServingError`` subclass must appear in
      ``_error_registry()``'s lazy import list;
    - every ``ServingError`` subclass declares class-level ``reason`` /
      ``retry_elsewhere`` (itself or via a subtree ancestor) and stays
      constructible as ``cls(message)`` — what ``decode_error`` does.

    Violations honor inline pragmas of the file they anchor in."""
    replica = client = server = errors_info = None
    all_classes = []
    for info in infos:
        if info is None:
            continue
        rp = info["relpath"]
        if rp.endswith(_WIRE_REPLICA_FILE):
            replica = info
        if rp.endswith(_WIRE_CLIENT_FILE):
            client = info
        if rp.endswith(_WIRE_SERVER_FILE):
            server = info
        if rp.endswith(_WIRE_ERRORS_FILE):
            errors_info = info
        for c in info["classes"]:
            all_classes.append((info, c))
    out = []

    def emit(info, line, symbol, message):
        disabled = info["pragmas"].get(line, ())
        if WIRE_CONTRACT in disabled or "all" in disabled:
            return
        out.append(Violation(rule=WIRE_CONTRACT, path=info["relpath"],
                             line=line, col=0, symbol=symbol,
                             message=message))

    # ServingError subtree, transitive by base NAME across files
    subtree, known, changed = {}, {"ServingError"}, True
    while changed:
        changed = False
        for info, c in all_classes:
            if c["name"] in known:
                continue
            if any(b in known for b in c["bases"]):
                known.add(c["name"])
                subtree[c["name"]] = (info, c)
                changed = True

    def _inherits(c, field):
        seen = set()
        while True:
            if c[field]:
                return True
            parent = next((b for b in c["bases"] if b in subtree
                           and b not in seen), None)
            if parent is None:
                return False
            seen.add(parent)
            c = subtree[parent][1]

    for name in sorted(subtree):
        info, c = subtree[name]
        if not _inherits(c, "has_reason") or not _inherits(c, "has_retry"):
            emit(info, c["line"], name,
                 f"ServingError subclass {name} does not declare "
                 f"class-level reason/retry_elsewhere — the wire encodes "
                 f"both, and inheriting the base defaults makes the "
                 f"remote routing decision wrong or ambiguous")
        if not c["ctor_ok"]:
            emit(info, c["line"], name,
                 f"ServingError subclass {name} is not constructible as "
                 f"{name}(message) — decode_error() rebuilds it exactly "
                 f"that way, so extra required __init__ params break "
                 f"error decoding at the first remote failure")

    if errors_info is not None:
        imports = errors_info["registry_imports"]
        by_module = {}
        for name in sorted(subtree):
            info, _c = subtree[name]
            if info is errors_info:
                continue
            mod = info["relpath"]
            mod = mod[:-3] if mod.endswith(".py") else mod
            by_module.setdefault(mod.replace("/", "."), []).append(name)
        for mod, names in sorted(by_module.items()):
            if mod not in imports:
                emit(errors_info, errors_info["registry_line"], mod,
                     f"_error_registry() never imports {mod}, which "
                     f"defines ServingError subclass(es) "
                     f"{', '.join(sorted(names))} — until the module is "
                     f"imported those errors decode as WireProtocolError "
                     f"(wrong type, wrong retry semantics); add the "
                     f"import to the lazy list in wire/errors.py")

    if replica is not None and client is not None:
        for m in sorted(replica["replica_methods"]):
            if m not in client["client_methods"]:
                emit(client, client["client_line"], f"WireReplica.{m}",
                     f"abstract Replica method {m}() has no WireReplica "
                     f"relay — a remote fleet silently loses the method "
                     f"(AttributeError / base default instead of the "
                     f"worker's answer); add the relay in wire/client.py")
            elif m not in client["client_ops"]:
                emit(client, client["client_methods"][m],
                     f"WireReplica.{m}",
                     f"WireReplica.{m}() never sends wire op {m!r} — the "
                     f"relay exists but does not cross the process "
                     f"boundary")
    if client is not None and server is not None:
        for op in sorted(client["client_ops"]):
            if op not in server["server_ops"]:
                emit(server, server["server_line"], f"ReplicaServer.{op}",
                     f"client relays send wire op {op!r} but "
                     f"ReplicaServer._dispatch/_unary never handles it — "
                     f"that is a runtime WireProtocolError('unknown wire "
                     f"op') under traffic; add the op to the server table")
        for op in sorted(server["server_ops"]):
            if op in client["client_ops"] or op in _WIRE_HANDLE_OPS:
                continue
            if replica is not None and op in replica["replica_methods"]:
                continue
            emit(server, server["server_ops"][op], f"ReplicaServer.{op}",
                 f"server wire op {op!r} has no client relay — dead "
                 f"(untestable) dispatch arm; remove it or add the "
                 f"WireReplica relay")
    if replica is not None and server is not None:
        for m in sorted(replica["replica_methods"]):
            if m in server["server_ops"]:
                continue
            if client is not None and m in client["client_ops"]:
                continue  # reported via the client->server check above
            emit(server, server["server_line"], f"ReplicaServer.{m}",
                 f"abstract Replica method {m}() has no ReplicaServer op "
                 f"— adding a Replica method requires wiring BOTH the "
                 f"client relay and the server dispatch arm (see the "
                 f"checklist in docs/LINTING.md)")
    return out


def _lint_one(path, source, relpath, only=None):
    """→ (violations, linter) for one file, pragma-filtered. The
    returned linter carries cross-file state (lock edges, wire info)."""
    linter = FileLinter(path, source, relpath=relpath)
    return linter.run(only=only), linter


def lint_file(path, source=None, relpath=None, only=None):
    """All unsuppressed-by-pragma violations for one file, including a
    per-file lock-cycle pass (lint_paths instead runs one merged pass
    over every file so cross-file cycles surface) and the wire-contract
    parity pass over this file's facts alone."""
    if source is None:
        with open(path) as fd:
            source = fd.read()
    violations, linter = _lint_one(path, source, relpath, only=only)
    if only is None or LOCK_ORDER_RULE in only:
        violations = violations + lock_cycle_violations(linter.lock_edges)
    if only is None or WIRE_CONTRACT in only:
        violations = violations + wire_contract_violations(
            [linter.wire_info])
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return violations


def _has_python_shebang(path):
    """Extensionless executable-script sniff: ``bin/ds_serve``-style
    entry points announce themselves with a ``#!...python`` first line
    and are held to every rule like any ``.py`` module."""
    try:
        with open(path, "rb") as fd:
            first = fd.readline(160)
    except OSError:
        return False
    return first.startswith(b"#!") and b"python" in first


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fn in sorted(filenames):
                    full = os.path.join(dirpath, fn)
                    if fn.endswith(".py"):
                        yield full
                    elif "." not in fn and _has_python_shebang(full):
                        yield full


def count_host_sync_pragmas(paths):
    """Number of ``# ds-lint: disable=…host-sync…`` pragma SITES (one
    per source line carrying the comment) under ``paths`` — the counted
    budget ``bin/ds_lint --only=host-sync`` ratchets against: every
    pragma is one deliberate host sync, so the count growing means a
    new sync site slipped into a hot path. Counted from raw lines, not
    parsed suppressions, so the standalone-pragma next-line rule in
    :func:`_parse_pragmas` cannot double-count a site."""
    count = 0
    for path in _iter_py_files(paths):
        with open(path) as fd:
            for line in fd:
                idx = line.find("# ds-lint:")
                if idx < 0:
                    continue
                body = line[idx + len("# ds-lint:"):]
                body = body.split("--", 1)[0].strip()
                if not body.startswith("disable="):
                    continue
                rules = {r.strip()
                         for r in body[len("disable="):].split(",")}
                if HOST_SYNC in rules or "all" in rules:
                    count += 1
    return count


def lint_paths(paths, baseline=None, root=None, only=None):
    """Lint every .py file under ``paths``. → (violations, baselined)
    where ``baselined`` counts suppressions consumed from the baseline
    set of (rule, relpath, symbol) triples. Lock-acquisition edges are
    merged across ALL files before the single cycle pass — an inversion
    in kv_tier/ against an order established in serving/ is a cycle."""
    baseline = baseline or set()
    root = root or os.getcwd()
    violations, baselined = [], 0
    all_edges = []
    wire_infos = []
    for path in _iter_py_files(paths):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as fd:
            source = fd.read()
        file_violations, linter = _lint_one(path, source, rel, only=only)
        all_edges.extend(linter.lock_edges)
        wire_infos.append(linter.wire_info)
        for v in file_violations:
            if (v.rule, v.path, v.symbol) in baseline:
                baselined += 1
                continue
            violations.append(v)
    merged = []
    if only is None or LOCK_ORDER_RULE in only:
        merged.extend(lock_cycle_violations(all_edges))
    if only is None or WIRE_CONTRACT in only:
        merged.extend(wire_contract_violations(wire_infos))
    for v in merged:
        if (v.rule, v.path, v.symbol) in baseline:
            baselined += 1
            continue
        violations.append(v)
    return violations, baselined
