"""graft-lint: AST-based TPU-hazard static analysis for deepspeed_tpu.

Five rule families (docs/LINTING.md has the catalog):

- jit-purity          Python side effects / traced-value branching
                      inside jit/pjit/shard_map/Pallas-traced functions
- host-sync           device→host synchronization in serving hot paths
- thread-shared-state unlocked writes to attributes shared across
                      threads (registry of known multi-thread classes)
- spec-consistency    PartitionSpec axis names vs the declared mesh
                      axes; fp32-constant dtype leaks in kernel/model code
- env-registry        DS_* env reads bypassing utils/env_registry.py

Suppression is either an inline pragma
``# ds-lint: disable=<rule>[,<rule>] -- reason`` on the offending line
(preferred — carries its reason in the code) or a baseline entry in
``tools/graft_lint/baseline.json`` (for pre-existing debt only).
"""

from tools.graft_lint.linter import (MESH_AXES, RULES, Violation, lint_file,
                                     lint_paths, load_baseline)

__all__ = ["MESH_AXES", "RULES", "Violation", "lint_file", "lint_paths",
           "load_baseline"]
