"""ds_lint command line: lint deepspeed_tpu/ for TPU hazards.

Exit codes: 0 clean, 1 violations, 2 usage/internal error (unknown
``--only`` rule, malformed baseline). ``--format json`` emits a
machine-readable report for CI; ``--list-knobs`` prints the DS_*
env-knob table from utils/env_registry.py (markdown, or the typed
knob schema with ``--format json``) instead of linting; ``--check-docs`` diffs that table against docs/MIGRATING.md
(the knob-docs rule, standalone); ``--only=rule1,rule2`` restricts the
run so the tier-1 gate can time rules individually;
``--update-baseline`` re-lints from scratch and rewrites the baseline
file with every current violation.
"""

import argparse
import importlib.util
import json
import os
import sys

from tools.graft_lint.linter import (HOST_SYNC, KNOB_DOCS, RULES,
                                     BaselineError, Violation,
                                     count_host_sync_pragmas, lint_paths,
                                     load_baseline)

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")
DEFAULT_KNOB_DOCS = os.path.join(REPO_ROOT, "docs", "MIGRATING.md")
DEFAULT_SYNC_BUDGET = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "host_sync_budget.json")


def _load_env_registry():
    """Load utils/env_registry.py straight from its file — the module
    is stdlib-only by contract, and loading it this way keeps ds_lint
    runnable without importing the jax-heavy package __init__."""
    path = os.path.join(REPO_ROOT, "deepspeed_tpu", "utils",
                        "env_registry.py")
    spec = importlib.util.spec_from_file_location("_ds_env_registry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def format_knobs_markdown():
    reg = _load_env_registry()
    lines = ["| Variable | Type | Default | Description |",
             "|---|---|---|---|"]
    for k in reg.all_knobs():
        lines.append(k.doc_row())
    return "\n".join(lines)


def format_knobs_json():
    """The typed knob schema (name, type, default, range/choices,
    tuning tag, doc row) — the same artifact the serving autotuner
    enumerates its search space from."""
    return json.dumps({"version": 1,
                       "knobs": _load_env_registry().knob_schema()},
                      indent=2)


def check_knob_docs(docs_path=None):
    """knob-docs rule: every knob in env_registry must have a row in
    the MIGRATING.md generated knob table and vice versa. → list of
    Violations (symbol = knob name) so drift keys into the baseline
    machinery like any other rule."""
    import re
    docs_path = docs_path or DEFAULT_KNOB_DOCS
    rel = os.path.relpath(docs_path, REPO_ROOT).replace(os.sep, "/")
    registered = {k.name for k in _load_env_registry().all_knobs()}
    try:
        with open(docs_path) as fd:
            text = fd.read()
    except OSError as err:
        return [Violation(rule=KNOB_DOCS, path=rel, line=1, col=0,
                          symbol="<file>",
                          message=f"knob table unreadable: {err}")]
    documented = {}  # name -> first table-row line number
    for i, line in enumerate(text.splitlines(), start=1):
        m = re.match(r"^\| `(DS_[A-Z0-9_]+)` \|", line)
        if m:
            documented.setdefault(m.group(1), i)
    out = []
    for name in sorted(registered - set(documented)):
        out.append(Violation(
            rule=KNOB_DOCS, path=rel, line=1, col=0, symbol=name,
            message=f"knob {name} is registered in env_registry.py but "
                    f"missing from the {rel} knob table — regenerate it "
                    f"with `bin/ds_lint --list-knobs`"))
    for name in sorted(set(documented) - registered):
        out.append(Violation(
            rule=KNOB_DOCS, path=rel, line=documented[name], col=0,
            symbol=name,
            message=f"knob {name} is documented in {rel} but no longer "
                    f"registered in env_registry.py — stale row, "
                    f"regenerate with `bin/ds_lint --list-knobs`"))
    return out


def check_sync_budget(paths, budget_path=None):
    """host-sync counted-pragma ratchet: the number of ``disable=
    host-sync`` pragma sites under ``paths`` may never exceed the
    recorded budget — every pragma is one deliberate host sync, so
    growth means a new sync slipped into a hot path. → list of
    Violations (empty when within budget). A count BELOW budget is
    clean but prints nothing; tighten with ``--update-sync-budget``."""
    budget_path = budget_path or DEFAULT_SYNC_BUDGET
    rel = os.path.relpath(budget_path, REPO_ROOT).replace(os.sep, "/")
    count = count_host_sync_pragmas(paths)
    try:
        with open(budget_path) as fd:
            data = json.load(fd)
        if not isinstance(data, dict) or data.get("version") != 1 or \
                not isinstance(data.get("pragma_budget"), int):
            raise ValueError("needs {version: 1, pragma_budget: <int>}")
        budget = data["pragma_budget"]
    except (OSError, ValueError, json.JSONDecodeError) as err:
        return [Violation(
            rule=HOST_SYNC, path=rel, line=1, col=0,
            symbol="<pragma-budget>",
            message=f"host-sync pragma budget unreadable ({err}) — "
                    f"record the current count with "
                    f"`bin/ds_lint --update-sync-budget`")]
    if count > budget:
        return [Violation(
            rule=HOST_SYNC, path=rel, line=1, col=0,
            symbol="<pragma-budget>",
            message=f"{count} host-sync pragma site(s) exceed the "
                    f"recorded budget of {budget} — a new deliberate "
                    f"sync entered a hot path; remove it, or justify it "
                    f"in review and raise the budget with "
                    f"`bin/ds_lint --update-sync-budget`")]
    return []


def write_sync_budget(paths, budget_path=None):
    budget_path = budget_path or DEFAULT_SYNC_BUDGET
    count = count_host_sync_pragmas(paths)
    with open(budget_path, "w") as fd:
        json.dump({"version": 1, "pragma_budget": count}, fd, indent=2)
        fd.write("\n")
    return count


def write_baseline(path, violations):
    """Rewrite ``path`` with a suppression entry per current violation
    (sorted, symbol-keyed — line numbers intentionally absent so the
    baseline survives unrelated edits)."""
    entries = sorted({(v.rule, v.path, v.symbol) for v in violations})
    payload = {"version": 1,
               "suppressions": [{"rule": r, "path": p, "symbol": s}
                                for r, p, s in entries]}
    with open(path, "w") as fd:
        json.dump(payload, fd, indent=2)
        fd.write("\n")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_lint",
        description="TPU-hazard static analysis for deepspeed_tpu "
                    f"(rules: {', '.join(RULES)})")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: deepspeed_tpu/ "
                             "plus the executable scripts in bin/)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON (default: tools/graft_lint/"
                             "baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report baselined violations too")
    parser.add_argument("--update-baseline", action="store_true",
                        help="re-lint ignoring the existing baseline and "
                             "rewrite it with every current violation")
    parser.add_argument("--only", default=None, metavar="RULE[,RULE...]",
                        help="run only these rules (per-rule CI timings)")
    parser.add_argument("--list-knobs", action="store_true",
                        help="print the DS_* env knob table and exit")
    parser.add_argument("--check-docs", action="store_true",
                        help="run only the knob-docs rule: diff the env "
                             "knob registry against the MIGRATING.md table")
    parser.add_argument("--update-sync-budget", action="store_true",
                        help="record the current host-sync pragma count as "
                             "the ratchet budget and exit")
    args = parser.parse_args(argv)

    if args.list_knobs:
        if args.format == "json":
            print(format_knobs_json())
        else:
            print(format_knobs_markdown())
        return 0

    only = None
    if args.only is not None:
        only = {r.strip() for r in args.only.split(",") if r.strip()}
        unknown = only - set(RULES)
        if unknown:
            print(f"ds_lint: unknown rule(s) {sorted(unknown)} — valid: "
                  f"{', '.join(RULES)}", file=sys.stderr)
            return 2

    if args.check_docs:
        violations = check_knob_docs()
        for v in violations:
            print(f"{v.path}:{v.line}: [{v.rule}] {v.symbol}: {v.message}")
        print(f"ds_lint: {len(violations)} knob-docs violation(s)")
        return 1 if violations else 0

    # default repo-wide scope: the package plus bin/ — the entry-point
    # scripts are extensionless but shebang-sniffed by _iter_py_files,
    # so they are held to every rule family too
    paths = args.paths or [os.path.join(REPO_ROOT, "deepspeed_tpu"),
                           os.path.join(REPO_ROOT, "bin")]
    if args.update_sync_budget:
        count = write_sync_budget(paths)
        print(f"ds_lint: host-sync pragma budget recorded at {count} "
              f"site(s) -> {DEFAULT_SYNC_BUDGET}")
        return 0
    baseline = set()
    if not args.update_baseline and not args.no_baseline \
            and os.path.exists(args.baseline):
        try:
            baseline = load_baseline(args.baseline)
        except BaselineError as err:
            print(f"ds_lint: malformed baseline {args.baseline}: {err}",
                  file=sys.stderr)
            return 2
    violations, baselined = lint_paths(paths, baseline=baseline,
                                       root=REPO_ROOT, only=only)
    # knob-docs is cross-artifact (registry vs docs), so it runs in the
    # default whole-repo invocation and under --only, not per-file
    if not args.paths and (only is None or KNOB_DOCS in only):
        for v in check_knob_docs():
            if (v.rule, v.path, v.symbol) in baseline:
                baselined += 1
            else:
                violations.append(v)
    # the host-sync pragma ratchet is likewise whole-repo: a count over
    # a partial path list would always read as "shrunk"
    if not args.paths and (only is None or HOST_SYNC in only):
        violations.extend(check_sync_budget(paths))

    if args.update_baseline:
        write_baseline(args.baseline, violations)
        print(f"ds_lint: baseline rewritten with {len(violations)} "
              f"suppression(s) -> {args.baseline}")
        return 0

    if args.format == "json":
        print(json.dumps({
            "violations": [v._asdict() for v in violations],
            "baselined": baselined,
        }, indent=2))
    else:
        for v in violations:
            print(f"{v.path}:{v.line}:{v.col}: [{v.rule}] {v.symbol}: "
                  f"{v.message}")
        note = f" ({baselined} baselined)" if baselined else ""
        print(f"ds_lint: {len(violations)} violation(s){note}")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
