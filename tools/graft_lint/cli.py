"""ds_lint command line: lint deepspeed_tpu/ for TPU hazards.

Exit codes: 0 clean, 1 violations, 2 usage/internal error. ``--format
json`` emits a machine-readable report for CI; ``--list-knobs`` prints
the DS_* env-knob table from utils/env_registry.py (markdown) instead
of linting.
"""

import argparse
import importlib.util
import json
import os
import sys

from tools.graft_lint.linter import RULES, lint_paths, load_baseline

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def _load_env_registry():
    """Load utils/env_registry.py straight from its file — the module
    is stdlib-only by contract, and loading it this way keeps ds_lint
    runnable without importing the jax-heavy package __init__."""
    path = os.path.join(REPO_ROOT, "deepspeed_tpu", "utils",
                        "env_registry.py")
    spec = importlib.util.spec_from_file_location("_ds_env_registry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def format_knobs_markdown():
    reg = _load_env_registry()
    lines = ["| Variable | Type | Default | Description |",
             "|---|---|---|---|"]
    for k in reg.all_knobs():
        lines.append(f"| `{k.name}` | {k.kind} | `{k.describe_default()}` "
                     f"| {k.description} (read by `{k.consumer}`) |")
    return "\n".join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="ds_lint",
        description="TPU-hazard static analysis for deepspeed_tpu "
                    f"(rules: {', '.join(RULES)})")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: deepspeed_tpu/)")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON (default: tools/graft_lint/"
                             "baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report baselined violations too")
    parser.add_argument("--list-knobs", action="store_true",
                        help="print the DS_* env knob table and exit")
    args = parser.parse_args(argv)

    if args.list_knobs:
        print(format_knobs_markdown())
        return 0

    paths = args.paths or [os.path.join(REPO_ROOT, "deepspeed_tpu")]
    baseline = set()
    if not args.no_baseline and os.path.exists(args.baseline):
        baseline = load_baseline(args.baseline)
    violations, baselined = lint_paths(paths, baseline=baseline,
                                       root=REPO_ROOT)

    if args.format == "json":
        print(json.dumps({
            "violations": [v._asdict() for v in violations],
            "baselined": baselined,
        }, indent=2))
    else:
        for v in violations:
            print(f"{v.path}:{v.line}:{v.col}: [{v.rule}] {v.symbol}: "
                  f"{v.message}")
        note = f" ({baselined} baselined)" if baselined else ""
        print(f"ds_lint: {len(violations)} violation(s){note}")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
